"""Fleet self-healing surface (docs/serving.md "Self-healing"): the
KV-allocator balance audit, the engine's liveness/condemnation surface
(heartbeat watermark, ``fail_inflight``, crash teardown that releases
every block), deadline propagation router→engine (expired-before-
dispatch never touches a replica; mid-decode expiry frees exactly its
blocks), the router circuit breaker's exponential backoff + half-open
probe on a fake clock, the supervisor's verdicts and dead-replica
replacement, poison-pill quarantine end-to-end over HTTP, and the chaos
conductor — ``--selftest`` smoke in tier-1, the full seeded scenario
catalog (including the kill -9 mid-decode acceptance scenario) in the
``--chaos`` lane (``@slow``)."""
import json
import time
import types
import urllib.error
import urllib.request

import jax
import pytest

from determined_clone_tpu import faults
from determined_clone_tpu.models import gpt
from determined_clone_tpu.serving import (
    BucketSpec,
    FleetSupervisor,
    KVCacheConfig,
    LeastLoadedRouter,
    PoisonPillRequest,
    ReplicaFailed,
    ServingFleet,
)
from determined_clone_tpu.serving.engine import InferenceEngine
from determined_clone_tpu.serving.http import FleetHTTPServer
from determined_clone_tpu.serving.kv_cache import BlockAllocator
from determined_clone_tpu.telemetry import MetricsRegistry

CFG = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32, n_heads=4,
                    d_ff=64, max_seq_len=48, remat=False,
                    attention_impl="mha")
BUCKETS = BucketSpec.build(2, 8)
CACHE = KVCacheConfig(num_blocks=16, block_size=8)
PROMPT = [1, 2, 3]
MAX_NEW = 8


@pytest.fixture(scope="module")
def params():
    return gpt.init(jax.random.PRNGKey(0), CFG)


def make_engine(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("cache", CACHE)
    return InferenceEngine(params, CFG, **kw)


def make_fleet(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("cache", CACHE)
    kw.setdefault("warmup", False)
    kw.setdefault("tracing", False)
    kw.setdefault("prefix_cache", False)
    return ServingFleet(params, CFG, **kw)


def wait_for(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


# -- allocator balance audit (satellite: leak detection) ---------------------

def test_allocator_outstanding_and_assert_balanced():
    alloc = BlockAllocator(CACHE)
    assert alloc.outstanding() == 0
    alloc.assert_balanced(0)
    blocks = alloc.allocate_blocks(3)
    assert alloc.outstanding() == 3
    alloc.assert_balanced(3)
    with pytest.raises(AssertionError) as ei:
        alloc.assert_balanced(0)
    # the audit names the held blocks — that's the leak diagnostic
    assert str(blocks[0]) in str(ei.value)
    for b in blocks:
        alloc.release([b])
    alloc.assert_balanced(0)


# -- router circuit breaker (satellite 1) ------------------------------------

class FakePort:
    def __init__(self, rid, queue=0, free=16, fail=None):
        self.replica_id = rid
        self.queue = queue
        self.free = free
        self.fail = fail
        self.admit = True
        self.submitted = 0

    def admitting(self):
        return self.admit

    def load(self):
        return (self.queue, -self.free)

    def submit(self, prompt, max_new_tokens, *, eos_token_id=None,
               request_id=None, deadline_t=None):
        if self.fail is not None:
            raise self.fail
        self.submitted += 1

        class Handle:
            def result(self, timeout=None):
                return None

        return Handle()


def test_breaker_exponential_backoff_and_half_open():
    now = [0.0]
    r = LeastLoadedRouter(exclude_cooldown_s=1.0, exclude_max_s=8.0,
                          clock=lambda: now[0])
    bad = FakePort("a", queue=0)   # least-loaded: tried first
    good = FakePort("b", queue=5)
    bad.fail = ConnectionError("boom")
    r.add(bad)
    r.add(good)

    # failure 1: dispatch fails over to b and opens a's breaker for the
    # base window
    r.submit(PROMPT, MAX_NEW)
    assert good.submitted == 1
    assert r.replica_states()["a"] == "open"
    now[0] = 0.5
    assert "a" in r.excluded()

    # window lapses -> half-open: exactly one probe is admitted, and its
    # failure re-opens at the DOUBLED window (2s, not 1s)
    now[0] = 1.1
    assert r.replica_states()["a"] == "half_open"
    r.submit(PROMPT, MAX_NEW)      # probe fails, lands on b again
    assert good.submitted == 2
    assert r.replica_states()["a"] == "open"
    now[0] = 2.5                   # base window would have lapsed ...
    assert "a" in r.excluded()     # ... but the doubled one has not
    now[0] = 3.5
    assert r.replica_states()["a"] == "half_open"

    # a successful probe closes the breaker and resets the backoff
    bad.fail = None
    bad.queue = 0
    r.submit(PROMPT, MAX_NEW)
    assert bad.submitted == 1
    assert r.replica_states()["a"] == "closed"
    assert "a" not in r.excluded()


def test_breaker_state_gauge_and_replica_failed_fails_over():
    now = [0.0]
    reg = MetricsRegistry()
    r = LeastLoadedRouter(reg, exclude_cooldown_s=1.0,
                          clock=lambda: now[0])
    dead = FakePort("a", queue=0, fail=ReplicaFailed("died", active=True))
    live = FakePort("b", queue=5)
    r.add(dead)
    r.add(live)
    # a dead-but-unremoved replica (ReplicaFailed) is a failover target,
    # never a client error
    r.submit(PROMPT, MAX_NEW)
    assert live.submitted == 1
    assert reg.gauge("router_replica_state",
                     labels={"replica": "a"}).value == 2  # open
    assert reg.gauge("router_replica_state",
                     labels={"replica": "b"}).value == 0  # closed
    now[0] = 1.5
    assert r.replica_states()["a"] == "half_open"
    # the gauge flips to half-open when the lapsed breaker admits its
    # probe (pick time), not on the clock alone
    r.pick()
    assert reg.gauge("router_replica_state",
                     labels={"replica": "a"}).value == 1


# -- deadline propagation (satellite 4) --------------------------------------

def test_deadline_expired_before_dispatch_never_touches_replica():
    r = LeastLoadedRouter()
    port = FakePort("a")
    r.add(port)
    with pytest.raises(TimeoutError, match="expired before dispatch"):
        r.submit(PROMPT, MAX_NEW, request_id="late",
                 deadline_t=time.monotonic() - 1.0)
    assert port.submitted == 0


def test_deadline_mid_decode_frees_blocks_and_counts(params):
    with make_engine(params) as eng:
        # warm the ladder so the deadline isn't eaten by compiles, then
        # submit work that cannot finish in time
        eng.generate(PROMPT, 2)
        h = eng.submit(PROMPT, MAX_NEW,
                       deadline_t=time.monotonic() - 0.001)
        res = h.result(timeout=30.0)
        assert res.finish_reason == "expired"
        eng.wait_idle(15.0)
        eng.assert_kv_balanced(0)
        assert eng.registry.counter(
            "serving_requests_expired_total").value == 1


# -- engine liveness + condemnation (tentpole plumbing) ----------------------

def test_liveness_snapshot_and_parked_is_not_wedged(params):
    sup = FleetSupervisor(types.SimpleNamespace(registry=MetricsRegistry()),
                          stale_after_s=0.1, start=False)
    with make_engine(params) as eng:
        eng.generate(PROMPT, 2)
        live = eng.liveness()
        assert live["thread_alive"] and live["fatal"] is None
        # the result is delivered before the scheduler finishes its
        # final pass, so pending may briefly linger — wait for the park
        assert wait_for(lambda: not eng.liveness()["pending"])
        # an idle parked scheduler has an arbitrarily stale beat — that
        # must read OK, not wedged
        time.sleep(0.3)
        assert sup.verdict(eng.liveness()) == "ok"


def test_supervisor_verdicts_pure():
    sup = FleetSupervisor(types.SimpleNamespace(registry=MetricsRegistry()),
                          stale_after_s=1.0, start=False)
    base = {"thread_alive": True, "fatal": None, "condemned": False,
            "warming": False, "pending": False, "beat_age_s": 0.0}
    assert sup.verdict(base) == "ok"
    assert sup.verdict({**base, "thread_alive": False}) == "dead"
    assert sup.verdict({**base, "fatal": RuntimeError("x")}) == "dead"
    assert sup.verdict({**base, "pending": True,
                        "beat_age_s": 2.0}) == "wedged"
    # warming replicas are never wedged (slow compiles are not failures)
    assert sup.verdict({**base, "pending": True, "warming": True,
                        "beat_age_s": 2.0}) == "ok"
    # stale beat with no pending work is a parked idle loop
    assert sup.verdict({**base, "beat_age_s": 2.0}) == "ok"


def test_fail_inflight_condemns_and_teardown_releases_blocks(params):
    eng = make_engine(params, iteration_floor_s=0.1)
    try:
        eng.generate(PROMPT, 2)  # warm: the floor paces real passes
        handles = [eng.submit(PROMPT, MAX_NEW, request_id=f"r{i}")
                   for i in range(3)]
        n = eng.fail_inflight("test condemnation")
        assert n == 3
        for h in handles:
            with pytest.raises(ReplicaFailed):
                h.result(timeout=10.0)
        # the scheduler notices the condemnation at its next wakeup and
        # tears down: thread dead, every block back in the pool
        assert wait_for(lambda: not eng.liveness()["thread_alive"])
        eng.assert_kv_balanced(0)
        # a dead engine refuses new work as ReplicaFailed (failover),
        # active=False — the request was never admitted, so no strike
        with pytest.raises(ReplicaFailed) as ei:
            eng.submit(PROMPT, MAX_NEW)
        assert ei.value.active is False
    finally:
        eng.close()


def test_injected_crash_mid_decode_releases_blocks(params):
    eng = make_engine(params, fault_scope="victim")
    plan = faults.activate(faults.plan_from_dict({
        "seed": 0,
        "rules": [{"point": "engine.step.victim", "action": "error",
                   "nth": 2, "times": 1}],
    }))
    try:
        with pytest.raises(ReplicaFailed):
            eng.submit(PROMPT, MAX_NEW).result(timeout=30.0)
        assert wait_for(lambda: eng.liveness()["fatal"] is not None)
        eng.assert_kv_balanced(0)
    finally:
        faults.deactivate(plan)
        eng.close()


# -- supervisor replaces a dead replica (tentpole) ---------------------------

def test_supervisor_replaces_dead_replica(params):
    fleet = make_fleet(params, name="heal")
    try:
        fleet.scale_up(2)
        sup = FleetSupervisor(fleet, start=False)
        assert sup.probe_once() == []  # healthy fleet: no actions
        victim = fleet.replicas()[0]
        victim.engine.fail_inflight("induced")
        actions = sup.probe_once()
        assert [a["verdict"] for a in actions] == ["dead"]
        assert actions[0]["replica"] == victim.replica_id
        # replaced: the victim is gone, a fresh replica took its slot
        ids = fleet.replica_ids()
        assert victim.replica_id not in ids
        assert len(ids) == 2
        assert fleet.registry.counter(
            "fleet_replica_replacements_total").value == 1
        incident = fleet.last_incident()
        assert incident["replica"] == victim.replica_id
        assert incident["reason"] == "dead"
        assert incident["leaked_blocks"] == 0
        # the health view carries the incident for dct fleet status
        view = fleet.health_view()
        assert view["incidents"] == 1
        assert view["last_incident"]["replica"] == victim.replica_id
        # the healed fleet serves (and the replacement warm-started off
        # the shared program cache)
        res, _ = fleet.handle_request(PROMPT, MAX_NEW, timeout=60.0)
        assert res.finish_reason in ("length", "eos")
    finally:
        fleet.close()


def test_supervisor_loop_thread_lifecycle(params):
    fleet = make_fleet(params, name="loop")
    try:
        fleet.scale_up(1)
        sup = fleet.start_supervisor(interval_s=0.05)
        assert sup.running
        assert fleet.health_view()["supervised"]
        # probe passes park the last-probe map at all-ok
        assert wait_for(lambda: sup.last_probe().get("loop-1") == "ok")
    finally:
        fleet.close()
    assert not sup.running  # fleet.close stops its supervisor


# -- poison pill quarantine, end to end over HTTP (tentpole) -----------------

def test_poison_pill_quarantined_and_http_422(params):
    fleet = make_fleet(params, name="pill", max_request_crashes=1)
    plan = faults.activate(faults.plan_from_dict({
        "seed": 0,
        "rules": [{"point": "engine.admit.req-poison", "action": "error",
                   "times": 0}],
    }), fleet.registry)
    try:
        fleet.scale_up(1)
        with FleetHTTPServer(fleet) as srv:
            def post(body, rid=None):
                req = urllib.request.Request(
                    f"{srv.url}/v1/generate",
                    data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                return urllib.request.urlopen(req, timeout=60.0)

            body = {"prompt": PROMPT, "max_new_tokens": MAX_NEW,
                    "request_id": "req-poison"}
            with pytest.raises(urllib.error.HTTPError) as ei:
                post(body)
            assert ei.value.code == 422
            payload = json.loads(ei.value.read().decode())
            assert "quarantined" in payload["error"]
            assert payload["diagnostics"]["crashes"] >= 1
            assert fleet.registry.counter(
                "fleet_requests_quarantined_total").value == 1

            # sticky: the resubmission is refused at the front door —
            # no replica touched, no new incident
            incidents = len(fleet.incidents())
            with pytest.raises(urllib.error.HTTPError) as ei:
                post(body)
            assert ei.value.code == 422
            assert len(fleet.incidents()) == incidents

            # deadline_s=0 is refused before dispatch: 504 even though
            # the pill killed the only replica
            with pytest.raises(urllib.error.HTTPError) as ei:
                post({"prompt": PROMPT, "max_new_tokens": MAX_NEW,
                      "deadline_s": 0.0})
            assert ei.value.code == 504
    finally:
        faults.deactivate(plan)
        fleet.close()


# -- chaos conductor ---------------------------------------------------------

def test_chaosfleet_selftest_smoke(params):
    from tools import chaosfleet
    # tier-1 smoke: the kill -9 mid-decode scenario end to end
    assert chaosfleet.main(["--selftest", "--requests", "2"]) == 0


def test_chaosfleet_cli_surface():
    from tools import chaosfleet
    assert chaosfleet.main(["--list"]) == 0
    assert chaosfleet.main(["--scenario", "no_such_scenario"]) == 2


@pytest.mark.slow
def test_chaos_full_catalog_deterministic(params):
    """The whole seeded scenario catalog (the --chaos lane's teeth):
    every scenario passes every invariant — zero lost accepted
    requests, bit-identical recovered outputs, zero leaked KV blocks,
    bounded MTTR — including the acceptance scenario
    (kill_replica_mid_decode at 2 replicas)."""
    from determined_clone_tpu.serving.chaos import run_scenarios
    results = run_scenarios(seed=0, params=params)
    failed = [
        f"{r.scenario}: {[c.name + ': ' + c.detail for c in r.checks if not c.ok]}"
        for r in results if not r.passed
    ]
    assert not failed, failed
    names = [r.scenario for r in results]
    assert "kill_replica_mid_decode" in names
