"""Schema-as-data expconf validation + v0->v1 shims.

≈ the reference's schema test cases (schemas/test_cases/*.yaml run by
schema_test.go) and legacy-shim tests (expconf/legacy.go behavior).
"""
import pytest

from determined_clone_tpu.config import schema, shims
from determined_clone_tpu.config.experiment import (
    ConfigError,
    ExperimentConfig,
)


GOOD = {
    "name": "exp",
    "entrypoint": "m:T",
    "searcher": {"name": "single", "metric": "loss",
                 "max_length": {"batches": 10}},
    "resources": {"slots_per_trial": 8, "topology": "v5e-8"},
    "checkpoint_storage": {"type": "gcs", "bucket": "b"},
}


class TestSchema:
    def test_valid_config_passes(self):
        assert schema.validate(GOOD) == []

    def test_unknown_top_level_key_reported_with_path(self):
        errors = schema.validate({**GOOD, "slotz": 3})
        assert len(errors) == 1
        assert "<config>.slotz" in errors[0] and "unknown field" in errors[0]

    def test_wrong_type_reported(self):
        errors = schema.validate({**GOOD, "max_restarts": "five"})
        assert any("max_restarts: expected integer" in e for e in errors)

    def test_bool_is_not_an_integer(self):
        errors = schema.validate({**GOOD, "max_restarts": True})
        assert errors

    def test_union_discriminator(self):
        errors = schema.validate(
            {**GOOD, "searcher": {"name": "mystery", "metric": "loss"}})
        assert any("searcher.name" in e for e in errors)

    def test_union_variant_requirements(self):
        errors = schema.validate(
            {**GOOD, "checkpoint_storage": {"type": "shared_fs"}})
        assert any("host_path: required" in e for e in errors)

    def test_nested_array_paths(self):
        errors = schema.validate(
            {**GOOD,
             "log_policies": [{"pattern": "x", "action": "explode"}]})
        assert any("log_policies[0].action" in e for e in errors)

    def test_enum(self):
        errors = schema.validate({**GOOD, "checkpoint_policy": "some"})
        assert any("checkpoint_policy" in e for e in errors)

    def test_discriminator_not_exempt_outside_unions(self):
        # "type"/"name" are only free passes at a union root, not in every
        # closed object
        errors = schema.validate({**GOOD, "resources": {"type": "x"}})
        assert any("resources.type" in e and "unknown" in e for e in errors)
        errors = schema.validate({**GOOD, "type": "bogus"})
        assert any("<config>.type" in e for e in errors)

    def test_log_policy_action_accepts_both_forms(self):
        base = {**GOOD, "log_policies": [
            {"pattern": "x", "action": "cancel_retries"}]}
        assert schema.validate(base) == []
        obj = {**GOOD, "log_policies": [
            {"pattern": "x", "action": {"type": "exclude_node"}}]}
        assert schema.validate(obj) == []
        bad = {**GOOD, "log_policies": [
            {"pattern": "x", "action": {"type": "explode"}}]}
        assert schema.validate(bad)

    def test_all_errors_reported_at_once(self):
        errors = schema.validate({
            **GOOD, "max_restarts": "x", "checkpoint_policy": "y",
            "bogus": 1})
        assert len(errors) == 3


class TestShims:
    def test_legacy_adaptive_searcher(self):
        cfg, notes = shims.shim({
            "searcher": {"name": "adaptive_simple", "metric": "loss",
                         "max_steps": 500}})
        assert cfg["searcher"]["name"] == "adaptive_asha"
        assert cfg["searcher"]["max_length"] == {"batches": 500}
        assert cfg["config_version"] == shims.CURRENT_VERSION
        assert len(notes) == 2

    def test_bare_int_lengths(self):
        cfg, notes = shims.shim({
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": 100},
            "min_validation_period": 50})
        assert cfg["searcher"]["max_length"] == {"batches": 100}
        assert cfg["min_validation_period"] == {"batches": 50}
        assert len(notes) == 2

    def test_flat_slots_and_batches_per_step(self):
        cfg, notes = shims.shim({"slots": 8, "batches_per_step": 200,
                                 "optimizations": {"aggregation": 2}})
        assert cfg["resources"]["slots_per_trial"] == 8
        assert cfg["scheduling_unit"] == 200
        assert "optimizations" not in cfg
        assert len(notes) == 3

    def test_current_version_untouched(self):
        raw = {"config_version": 1,
               "searcher": {"name": "single", "metric": "loss",
                            "max_length": 100}}
        cfg, notes = shims.shim(raw)
        assert cfg is raw and notes == []  # modern configs never rewritten

    def test_input_not_mutated(self):
        raw = {"slots": 4}
        shims.shim(raw)
        assert raw == {"slots": 4}

    def test_conflicting_slots_is_an_error(self):
        with pytest.raises(ValueError, match="both"):
            shims.shim({"slots": 8,
                        "resources": {"slots_per_trial": 4}})
        # agreeing values are fine
        cfg, _ = shims.shim({"slots": 4,
                             "resources": {"slots_per_trial": 4}})
        assert cfg["resources"]["slots_per_trial"] == 4


class TestPipeline:
    def test_from_dict_runs_shims_then_schema(self):
        cfg = ExperimentConfig.from_dict({
            "entrypoint": "m:T",
            "searcher": {"name": "adaptive", "metric": "loss",
                         "max_steps": 64},
            "slots": 2,
        })
        assert cfg.searcher.name == "adaptive_asha"
        assert cfg.searcher.max_length.value == 64
        assert cfg.resources.slots_per_trial == 2
        assert cfg.deprecations  # surfaced, not silent

    def test_from_dict_rejects_unknown_keys_with_paths(self):
        with pytest.raises(ConfigError) as err:
            ExperimentConfig.from_dict({**GOOD, "scheduler_unit": 3})
        assert "scheduler_unit" in str(err.value)

    def test_modern_config_requires_modern_spellings(self):
        # a config_version 1 config skips the shims: v0 spellings now fail
        with pytest.raises(ConfigError):
            ExperimentConfig.from_dict({
                "config_version": 1, "entrypoint": "m:T",
                "searcher": {"name": "adaptive", "metric": "loss"}})
