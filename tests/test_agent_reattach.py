"""Container runtime + reattach-after-restart e2e.

≈ the reference's container reattach (agent/internal/containers/
manager.go:76 + e2e managed-cluster agent-restart tests): with the
container runtime, tasks run detached under a supervisor, survive the
agent being SIGKILLed, and a restarted agent re-adopts them from its state
file — the master never sees the task exit.
"""
import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture()
def cluster(tmp_path):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    workdir = tmp_path / "agent-work"
    workdir.mkdir()

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp_path / "master-data"), "--agent-timeout", "30"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    def spawn_agent():
        return subprocess.Popen(
            [str(AGENT_BIN), "--master-port", str(port), "--id", "ra-agent",
             "--work-dir", str(workdir), "--runtime", "container"],
            cwd=str(workdir),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        )

    agent = spawn_agent()

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    state = {"agent": agent}
    yield {"session": session, "tmp": tmp_path, "workdir": workdir,
           "spawn_agent": spawn_agent, "state": state}

    state["agent"].kill()
    master.kill()
    state["agent"].wait(timeout=10)
    master.wait(timeout=10)


def wait_for(predicate, timeout=60, interval=0.3, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def test_task_survives_agent_restart(cluster):
    session = cluster["session"]
    marker = cluster["tmp"] / "survived.txt"
    # a task that takes ~6s and then writes a marker: long enough to kill
    # the agent mid-flight, short enough for the test
    task = session.create_task(
        "command", name="survivor",
        cmd=["python", "-c",
             "import time; time.sleep(6); "
             f"open({str(marker)!r}, 'w').write('alive')"],
    )
    tid = task["id"]
    wait_for(lambda: session.get_task(tid)["state"] == "RUNNING",
             desc="task running")

    # SIGKILL the agent mid-task: with the container runtime the
    # supervisor+task pair keeps running (own session, no PDEATHSIG)
    agent = cluster["state"]["agent"]
    agent.kill()
    agent.wait(timeout=10)
    assert not marker.exists(), "task finished before the agent was killed"
    # the state file the restarted agent reattaches from
    assert (cluster["workdir"] / "agent-state.json").exists()

    # restart the agent: it must re-adopt the task, keep reporting it
    # running, and deliver the real exit when it completes
    cluster["state"]["agent"] = cluster["spawn_agent"]()
    final = wait_for(
        lambda: (lambda t: t if t["state"] == "COMPLETED" else None)(
            session.get_task(tid)),
        timeout=60, desc="task completion after reattach",
    )
    assert final["exit_code"] == 0
    assert marker.read_text() == "alive"
    # the master never saw a failure: restarts/kill path untouched
    assert final["state"] == "COMPLETED"


def test_exit_while_agent_down_is_reported_on_restart(cluster):
    session = cluster["session"]
    task = session.create_task(
        "command", name="fast-exit",
        cmd=["python", "-c", "import time; time.sleep(1.5)"],
    )
    tid = task["id"]
    wait_for(lambda: session.get_task(tid)["state"] == "RUNNING",
             desc="task running")
    agent = cluster["state"]["agent"]
    agent.kill()
    agent.wait(timeout=10)
    # let the task finish while no agent is watching
    time.sleep(3)
    cluster["state"]["agent"] = cluster["spawn_agent"]()
    final = wait_for(
        lambda: (lambda t: t if t["state"] == "COMPLETED" else None)(
            session.get_task(tid)),
        timeout=30, desc="exit reported after restart",
    )
    # the supervisor outlived the agent and recorded the real exit code
    assert final["exit_code"] == 0
