"""NTSC tasks (shell/command/notebook/tensorboard) + master reverse proxy.

≈ the reference's NTSC e2e behavior: task create → allocation → container →
proxy registration → master routes /proxy/:taskID/* (master/internal/command,
master/internal/proxy/proxy.go), idle watcher kill (task/idle/watcher.go).
"""
import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("ntsc")
    workdir = tmp / "agent-work"
    workdir.mkdir()

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "ntsc-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


def wait_for(predicate, timeout=60, interval=0.3, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def wait_proxied(session, task_id):
    """Task RUNNING with a registered proxy address."""
    return wait_for(
        lambda: (lambda t: t if t["state"] == "RUNNING" and
                 t["proxy_address"] else None)(session.get_task(task_id)),
        desc=f"{task_id} running + proxied",
    )


def test_shell_task_exec_through_proxy(cluster):
    session = cluster["session"]
    task = session.create_task("shell", name="sh1")
    assert task["task_type"] == "shell"
    assert task["slots"] == 0

    wait_proxied(session, task["id"])
    out = session.proxy(task["id"], "/exec", "POST",
                        {"cmd": ["echo", "hello-ntsc"]})
    assert out["code"] == 0
    assert out["stdout"].strip() == "hello-ntsc"

    # landing page through the proxy
    page = session.proxy(task["id"], "/")
    assert page["mode"] == "shell"

    session.kill_task(task["id"])
    wait_for(
        lambda: session.get_task(task["id"])["state"] == "CANCELED",
        desc="task canceled",
    )


def test_command_task_runs_user_argv(cluster):
    session = cluster["session"]
    marker = cluster["tmp"] / "cmd-ran.txt"
    task = session.create_task(
        "command", name="cmd1",
        cmd=["python", "-c",
             f"open({str(marker)!r}, 'w').write('done')"],
    )
    wait_for(
        lambda: session.get_task(task["id"])["state"] == "COMPLETED",
        desc="command task completion",
    )
    assert marker.read_text() == "done"
    assert session.get_task(task["id"])["exit_code"] == 0


def test_command_task_requires_argv(cluster):
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError) as err:
        cluster["session"].create_task("command", name="bad")
    assert err.value.status == 400


def test_task_listing_and_filter(cluster):
    session = cluster["session"]
    task = session.create_task("notebook", name="nb1")
    all_ids = {t["id"] for t in session.list_tasks()}
    assert task["id"] in all_ids
    nb_ids = {t["id"] for t in session.list_tasks("notebook")}
    assert task["id"] in nb_ids
    sh_ids = {t["id"] for t in session.list_tasks("shell")}
    assert task["id"] not in sh_ids

    # notebook fallback server responds through the proxy
    wait_proxied(session, task["id"])
    page = session.proxy(task["id"], "/")
    assert page["mode"] == "notebook"
    session.kill_task(task["id"])


def test_idle_watcher_reaps_idle_task(cluster):
    session = cluster["session"]
    task = session.create_task("shell", name="idle1", idle_timeout=2.0)
    wait_proxied(session, task["id"])
    # no proxy traffic → the idle watcher cancels it (idle/watcher.go)
    final = wait_for(
        lambda: (lambda t: t if t["state"] == "CANCELED" else None)(
            session.get_task(task["id"])),
        timeout=30, desc="idle task reaped",
    )
    assert final["state"] == "CANCELED"


def test_tensorboard_task_serves_metric_data(cluster):
    session = cluster["session"]
    task = session.create_task("tensorboard", name="tb1", experiment_ids=[])
    wait_proxied(session, task["id"])
    data = session.proxy(task["id"], "/data")
    assert data == {"experiments": {}}
    session.kill_task(task["id"])
