"""The five BASELINE.json configs run end-to-end through `det experiment
create` on artificial slots — the reference's nightly pattern
(e2e_tests/tests/nightly/test_distributed.py:15 submits the committed
example configs and waits for COMPLETED).

Each example directory under examples/ is submitted with its committed
YAML + its model-def context, scaled down via --config-override (the CLI's
dotted-path overrides) so CI on one CPU core finishes in minutes; the
committed configs keep real-scale hyperparameters for hardware runs.
"""
import json
import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
EXAMPLES = REPO / "examples"
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("examples-cluster")
    workdir = tmp / "agent-work"
    workdir.mkdir()

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        # the distributed examples want 8 chips; give the trial processes
        # a virtual 8-device host (the conftest trick, but for the agent's
        # children)
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "8",
        "DCT_AGENT_TOPOLOGY": "v5e-8",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id",
         "examples-agent", "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port,
           "master_addr": f"127.0.0.1:{port}"}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


@pytest.fixture()
def det(cluster, tmp_path, monkeypatch):
    monkeypatch.setenv("HOME", str(tmp_path))  # isolate ~/.dct auth store
    from determined_clone_tpu.cli import main

    def run(*argv):
        return main(["-m", cluster["master_addr"], *argv])

    return run


def _submit(cluster, det, config_path, model_dir, overrides, name):
    """`det experiment create -f`: returns (exit_code, experiment_detail)."""
    args = ["experiment", "create", str(config_path), str(model_dir),
            "--config-override", f"name={name}",
            "--config-override",
            "checkpoint_storage.type=shared_fs",
            "--config-override",
            f"checkpoint_storage.host_path={cluster['tmp'] / 'ckpts'}",
            "-f", "--timeout", "420"]
    for ov in overrides:
        args += ["--config-override", ov]
    rc = det(*args)
    session = cluster["session"]
    exps = [e for e in session.list_experiments() if e["name"] == name]
    assert exps, f"experiment {name} not found after create"
    detail = session.get_experiment(exps[-1]["id"])
    if rc != 0:  # surface trial logs before failing
        for t in detail["trials"]:
            logs = session.task_logs(f"trial-{t['id']}.0")
            print(f"--- trial {t['id']} logs ---")
            for line in logs[-40:]:
                print(json.dumps(line)[:400])
    return rc, detail


TINY_COMMON = [
    "scheduling_unit=2",
    "min_validation_period.batches=4",
    "max_restarts=0",
]


def test_mnist_const(cluster, det):
    rc, detail = _submit(
        cluster, det, EXAMPLES / "mnist" / "const.yaml", EXAMPLES / "mnist",
        TINY_COMMON + [
            "searcher.max_length.batches=8",
            "hyperparameters.global_batch_size=16",
            "hyperparameters.n_filters_1=4",
            "hyperparameters.n_filters_2=8",
        ], name="ex-mnist-const")
    assert rc == 0 and detail["experiment"]["state"] == "COMPLETED"
    [trial] = detail["trials"]
    # real held-out digits accuracy was reported through the platform
    metrics = cluster["session"].trial_metrics(trial["id"])
    val = [m for m in metrics if m["group"] == "validation"]
    assert val and "accuracy" in val[-1]["metrics"]
    assert trial["latest_checkpoint"]


def test_mnist_distributed_dp8(cluster, det):
    rc, detail = _submit(
        cluster, det, EXAMPLES / "mnist" / "distributed.yaml",
        EXAMPLES / "mnist",
        TINY_COMMON + [
            "searcher.max_length.batches=6",
            "hyperparameters.global_batch_size=16",  # 2 per virtual chip
            "hyperparameters.n_filters_1=4",
            "hyperparameters.n_filters_2=8",
        ], name="ex-mnist-dp8")
    assert rc == 0 and detail["experiment"]["state"] == "COMPLETED"


def test_resnet_distributed(cluster, det):
    rc, detail = _submit(
        cluster, det, EXAMPLES / "resnet50" / "distributed.yaml",
        EXAMPLES / "resnet50",
        TINY_COMMON + [
            "searcher.max_length.batches=4",
            "hyperparameters.global_batch_size=16",
            "hyperparameters.depth=26",
            "hyperparameters.width=8",
            "hyperparameters.n_classes=10",
            "hyperparameters.image_size=16",
            "hyperparameters.n_train=128",
        ], name="ex-resnet")
    assert rc == 0 and detail["experiment"]["state"] == "COMPLETED"


def test_bert_core_api(cluster, det):
    rc, detail = _submit(
        cluster, det, EXAMPLES / "bert_finetune" / "const.yaml",
        EXAMPLES / "bert_finetune",
        ["max_restarts=0",
         "searcher.max_length.batches=20",
         "hyperparameters.global_batch_size=8",
         "hyperparameters.n_layers=2",
         "hyperparameters.d_model=32",
         "hyperparameters.n_heads=2",
         "hyperparameters.d_ff=64",
         "hyperparameters.vocab_size=128",
         "hyperparameters.seq_len=32",
         ], name="ex-bert-core")
    assert rc == 0 and detail["experiment"]["state"] == "COMPLETED"
    [trial] = detail["trials"]
    # the Core API script reported validation + completed the searcher op
    metrics = cluster["session"].trial_metrics(trial["id"])
    val = [m for m in metrics if m["group"] == "validation"]
    assert val and "accuracy" in val[-1]["metrics"]
    # and uploaded a checkpoint through core_context.checkpoint
    assert trial["latest_checkpoint"]


def test_bert_core_api_resume_local(tmp_path):
    """The restore path the cluster test can't reach (max_restarts=0 there):
    run the Core API script locally, then re-run it pointed at the uploaded
    checkpoint — it must resume batches_done and complete the (already
    satisfied) searcher op without retraining."""
    import sys

    sys.path.insert(0, str(EXAMPLES / "bert_finetune"))
    try:
        import train_bert
    finally:
        sys.path.pop(0)
    from determined_clone_tpu import core
    from determined_clone_tpu.config.experiment import ExperimentConfig

    config = ExperimentConfig.from_dict({
        "name": "bert-resume-local",
        "entrypoint": "train_bert:main",
        "searcher": {"name": "single", "metric": "accuracy",
                     "smaller_is_better": False,
                     "max_length": {"batches": 3}},
        "hyperparameters": {},
    })
    hp = {"global_batch_size": 4, "n_layers": 1, "d_model": 16,
          "n_heads": 2, "d_ff": 32, "vocab_size": 64, "seq_len": 16}

    class Info:
        hparams = hp
        latest_checkpoint = None

    with core.init(config=config, storage_path=str(tmp_path)) as cctx:
        res = train_bert.main(cctx, Info)
    assert res == {"state": "completed", "batches": 3}

    recs = [json.loads(line)
            for line in open(tmp_path / "checkpoints.jsonl")]
    assert recs and recs[-1]["metadata"]["steps_completed"] == 3

    class Resumed:
        hparams = hp
        latest_checkpoint = recs[-1]["storage_id"]

    with core.init(config=config, storage_path=str(tmp_path)) as cctx:
        res2 = train_bert.main(cctx, Resumed)
    # op target (3) already met by the restored batches_done: no retraining
    assert res2 == {"state": "completed", "batches": 3}


def test_gpt_fsdp(cluster, det):
    rc, detail = _submit(
        cluster, det, EXAMPLES / "gpt_fsdp" / "fsdp.yaml",
        EXAMPLES / "gpt_fsdp",
        TINY_COMMON + [
            "searcher.max_length.batches=4",
            "hyperparameters.global_batch_size=8",
            "hyperparameters.n_layers=2",
            "hyperparameters.d_model=64",
            "hyperparameters.n_heads=4",
            "hyperparameters.d_ff=128",
            "hyperparameters.vocab_size=512",
            "hyperparameters.seq_len=64",
            "hyperparameters.n_train_tokens=10000",
            "hyperparameters.remat=false",
            "hyperparameters.attention_impl=mha",
        ], name="ex-gpt-fsdp")
    assert rc == 0 and detail["experiment"]["state"] == "COMPLETED"
