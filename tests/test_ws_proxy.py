"""WebSocket upgrade through the master's reverse proxy (VERDICT r3 #4).

The reference proxies WebSocket and raw TCP between the browser and task
containers (/root/reference/master/internal/proxy/ws.go, tcp.go). Here the
master detects Connection: Upgrade on /proxy/<alloc>/..., replays the
request head to the task server, and splices the two sockets with a
dedicated relay thread — so real jupyter kernel channels (and live
shells) work through the authenticated proxy instead of request/response
buffering.

The test implements just enough RFC6455 by hand (no websocket deps in the
image): the echo server computes Sec-WebSocket-Accept and echoes text
frames; the client masks its frames as the RFC requires.
"""
import base64
import hashlib
import json
import os
import socket
import struct
import subprocess
import threading
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"

WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("wsproxy")
    workdir = tmp / "agent-work"
    workdir.mkdir()

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "ws-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "port": port}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


# -- minimal RFC6455 framing -------------------------------------------------

def ws_encode(payload: bytes, mask: bool) -> bytes:
    head = bytes([0x81])  # FIN + text
    n = len(payload)
    mbit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mbit | n])
    elif n < 65536:
        head += bytes([mbit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mbit | 127]) + struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        return head + key + bytes(b ^ key[i % 4]
                                  for i, b in enumerate(payload))
    return head + payload


def recv_exact(sock, n: int) -> bytes:
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        data += chunk
    return data


def ws_decode(sock) -> bytes:
    b0, b1 = recv_exact(sock, 2)
    masked = b1 & 0x80
    n = b1 & 0x7F
    if n == 126:
        n = struct.unpack(">H", recv_exact(sock, 2))[0]
    elif n == 127:
        n = struct.unpack(">Q", recv_exact(sock, 8))[0]
    key = recv_exact(sock, 4) if masked else None
    payload = recv_exact(sock, n)
    if key:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return payload


class WsEchoServer:
    """Accepts one upgrade, records the request head, echoes text frames."""

    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.request_head = b""
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        conn, _ = self.sock.accept()
        try:
            head = b""
            while b"\r\n\r\n" not in head:
                chunk = conn.recv(4096)
                if not chunk:
                    return
                head += chunk
            self.request_head = head
            key = next(
                line.split(b":", 1)[1].strip()
                for line in head.split(b"\r\n")
                if line.lower().startswith(b"sec-websocket-key"))
            accept = base64.b64encode(hashlib.sha1(
                key + WS_GUID.encode()).digest()).decode()
            conn.sendall(
                ("HTTP/1.1 101 Switching Protocols\r\n"
                 "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                 f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())
            while True:
                payload = ws_decode(conn)
                conn.sendall(ws_encode(b"echo:" + payload, mask=False))
        except (ConnectionError, StopIteration, OSError):
            pass
        finally:
            conn.close()

    def close(self):
        self.sock.close()


def test_websocket_roundtrip_through_proxy(cluster):
    session = cluster["session"]
    port = cluster["port"]
    task = session.create_task("shell", name="ws-sh")
    tid = task["id"]
    deadline = time.time() + 30
    while time.time() < deadline:
        if session.get_task(tid)["state"] in ("RUNNING", "PULLING"):
            break
        time.sleep(0.2)

    echo = WsEchoServer()
    # point the allocation's proxy at the echo server (what a real task
    # server does on startup)
    session.post(f"/api/v1/allocations/{tid}/proxy",
                 {"address": f"127.0.0.1:{echo.port}"})

    client = socket.create_connection(("127.0.0.1", port), timeout=15)
    try:
        key = base64.b64encode(os.urandom(16)).decode()
        client.sendall(
            (f"GET /proxy/{tid}/kernels/ws HTTP/1.1\r\n"
             f"Host: 127.0.0.1:{port}\r\n"
             "Connection: Upgrade\r\nUpgrade: websocket\r\n"
             "Sec-WebSocket-Version: 13\r\n"
             f"Sec-WebSocket-Key: {key}\r\n\r\n").encode())
        # 101 comes from the task server THROUGH the relay
        head = b""
        while b"\r\n\r\n" not in head:
            chunk = client.recv(4096)
            assert chunk, "proxy closed before the 101"
            head += chunk
        status_line = head.split(b"\r\n", 1)[0]
        assert b"101" in status_line, head
        expect = base64.b64encode(hashlib.sha1(
            (key + WS_GUID).encode()).digest())
        assert expect in head  # handshake passed through unaltered

        # full frame round trips, both directions, multiple times
        for i in range(3):
            msg = f"ping-{i}".encode()
            client.sendall(ws_encode(msg, mask=True))
            assert ws_decode(client) == b"echo:" + msg

        # the upstream saw the alloc token injected by the master, and
        # never the Authorization header
        assert b"x-alloc-token:" in echo.request_head.lower()
        assert b"authorization" not in echo.request_head.lower()
    finally:
        client.close()
        echo.close()
    session.kill_task(tid)


def test_plain_http_proxy_still_buffers(cluster):
    """Non-upgrade requests keep the request/response relay path."""
    session = cluster["session"]
    task = session.create_task("shell", name="ws-plain")
    tid = task["id"]
    deadline = time.time() + 30
    while time.time() < deadline:
        t = session.get_task(tid)
        if t["state"] == "RUNNING" and t.get("proxy_address"):
            break
        time.sleep(0.2)
    out = session.proxy(tid, "/", "GET")
    assert out  # the task server's landing payload came through
    session.kill_task(tid)
