"""Flight-recorder tests: the crash black box (telemetry/flight.py).

Fast unit tests cover the segment ring mechanics (rotation, bound,
read-back, torn-line tolerance, write-fault drop policy); the slow chaos
test kill -9s a real training subprocess and proves `dct debug flight`
recovers the final pre-kill steps as a valid Chrome trace — the property
the whole module exists for.
"""
import json
import os
import subprocess
import sys

import pytest

from determined_clone_tpu import faults
from determined_clone_tpu.telemetry import (
    FlightRecorder,
    MetricsRegistry,
    Telemetry,
    flight_summary,
    flight_to_chrome_trace,
    read_flight,
    validate_chrome_trace,
)
from determined_clone_tpu.telemetry.flight import _segment_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spans(directory):
    return [r for r in read_flight(str(directory)) if r.get("kind") == "span"]


# ---------------------------------------------------------------------------
# Segment ring mechanics
# ---------------------------------------------------------------------------

class TestSegmentRing:
    def test_rotation_and_ring_bound(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), segment_events=4, max_segments=3)
        for i in range(40):
            rec.record_span({"name": "step", "ts_us": float(i),
                             "dur_us": 1.0, "tid": 1, "tname": "t",
                             "depth": 0})
        rec.close()
        paths = _segment_paths(str(tmp_path))
        assert 1 <= len(paths) <= 3
        # filenames strictly increasing and the OLDEST were deleted: after
        # 40 records at 4/segment the surviving ring starts well past 1
        seqs = [int(os.path.basename(p).split("-")[1].split(".")[0])
                for p in paths]
        assert seqs == sorted(seqs)
        assert seqs[0] > 1
        # every surviving record is still readable, newest included
        spans = _spans(tmp_path)
        assert spans and spans[-1]["ts_us"] == 39.0

    def test_read_back_and_summary(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), segment_events=64)
        rec.record_span({"name": "train_dispatch", "ts_us": 0.0,
                         "dur_us": 5.0, "tid": 1, "tname": "t", "depth": 0})
        rec.record_span({"name": "dataload_wait", "ts_us": 6.0,
                         "dur_us": 1.0, "tid": 1, "tname": "t", "depth": 0})
        rec.record_metrics({"batches_trained": {"value": 8.0}},
                           batches_trained=8)
        rec.close()
        s = flight_summary(str(tmp_path))
        assert s["segments"] == 1
        assert s["spans"] == 2
        assert s["metric_snapshots"] == 1
        assert s["span_names"] == {"train_dispatch": 1, "dataload_wait": 1}
        assert s["last_batches_trained"] == 8
        assert s["last_snapshot"]["batches_trained"]["value"] == 8.0

    def test_resume_appends_after_restart(self, tmp_path):
        """A restart leg must append new segments, not clobber the
        previous leg's evidence (the crash being debugged happened there)."""
        leg1 = FlightRecorder(str(tmp_path), segment_events=64)
        leg1.record_span({"name": "before_crash", "ts_us": 0.0,
                          "dur_us": 1.0, "tid": 1, "tname": "t", "depth": 0})
        leg1.close()
        leg2 = FlightRecorder(str(tmp_path), segment_events=64)
        leg2.record_span({"name": "after_restart", "ts_us": 0.0,
                          "dur_us": 1.0, "tid": 1, "tname": "t", "depth": 0})
        leg2.close()
        names = [r["name"] for r in _spans(tmp_path)]
        assert names == ["before_crash", "after_restart"]
        assert flight_summary(str(tmp_path))["segments"] == 2

    def test_torn_final_line_skipped(self, tmp_path):
        """A kill mid-write leaves a partial JSON line at the tail; the
        reader must skip it and keep everything before it."""
        rec = FlightRecorder(str(tmp_path), segment_events=64)
        for i in range(3):
            rec.record_span({"name": f"s{i}", "ts_us": float(i),
                             "dur_us": 1.0, "tid": 1, "tname": "t",
                             "depth": 0})
        rec.close()
        path = _segment_paths(str(tmp_path))[-1]
        with open(path, "a") as f:
            f.write('{"kind": "span", "name": "torn')  # no newline, no close
        names = [r["name"] for r in _spans(tmp_path)]
        assert names == ["s0", "s1", "s2"]

    def test_kill9_durability_no_close(self, tmp_path):
        """Line buffering means records written before an os._exit-style
        death are on disk without any close()/flush() having run."""
        rec = FlightRecorder(str(tmp_path), segment_events=64)
        rec.record_span({"name": "last_words", "ts_us": 0.0, "dur_us": 1.0,
                         "tid": 1, "tname": "t", "depth": 0})
        # no close(): read through the filesystem as a post-mortem would
        assert [r["name"] for r in _spans(tmp_path)] == ["last_words"]


# ---------------------------------------------------------------------------
# Failure policy: a write error drops the record, never raises
# ---------------------------------------------------------------------------

class TestWriteFaults:
    def test_injected_write_error_drops_and_counts(self, tmp_path):
        reg = MetricsRegistry()
        rec = FlightRecorder(str(tmp_path), segment_events=64, registry=reg)
        with faults.plan_active({"rules": [
                {"point": "flight.write", "action": "error", "exc": "io",
                 "nth": 2, "times": 1}]}):
            rec.record_span({"name": "ok1", "ts_us": 0.0, "dur_us": 1.0,
                             "tid": 1, "tname": "t", "depth": 0})
            rec.record_span({"name": "lost", "ts_us": 1.0, "dur_us": 1.0,
                             "tid": 1, "tname": "t", "depth": 0})  # dropped
            rec.record_span({"name": "ok2", "ts_us": 2.0, "dur_us": 1.0,
                             "tid": 1, "tname": "t", "depth": 0})
        rec.close()
        assert rec.records_dropped == 1
        assert reg.counter("flight_records_dropped").value == 1
        assert [r["name"] for r in _spans(tmp_path)] == ["ok1", "ok2"]

    def test_unserializable_record_dropped(self, tmp_path):
        rec = FlightRecorder(str(tmp_path), segment_events=64)
        rec.record_span({"name": "bad", "payload": {1, 2, 3},
                         "cycle": None})
        # sets stringify via default=str — build a real cycle instead
        cyc = {}
        cyc["self"] = cyc
        rec.record_span(cyc)
        rec.close()
        assert rec.records_dropped == 1  # only the cycle is unserializable


# ---------------------------------------------------------------------------
# Telemetry integration: tracer sink + identity -> valid Chrome trace
# ---------------------------------------------------------------------------

class TestFlightTrace:
    def test_tracer_sink_to_valid_chrome_trace(self, tmp_path):
        tel = Telemetry(enabled=True, trace_id="exp-1",
                        process_name="trial-1")
        tel.attach_flight(FlightRecorder(str(tmp_path), segment_events=64))
        with tel.tracer.span("train_dispatch", step=0):
            pass
        tel.tracer.instant("step_time_anomaly", duration_s=0.5)
        tel.close()
        trace = flight_to_chrome_trace(str(tmp_path))
        assert validate_chrome_trace(trace) == []
        names = {e["name"] for e in trace["traceEvents"]}
        assert "train_dispatch" in names
        assert "step_time_anomaly" in names
        assert trace["otherData"]["source"] == "flight_recorder"

    def test_sink_sees_records_past_tracer_cap(self, tmp_path):
        """The in-memory ring keeps the HEAD; the black box must keep the
        TAIL — records past max_events still reach the flight sink."""
        tel = Telemetry(enabled=True, max_events=4)
        tel.attach_flight(FlightRecorder(str(tmp_path), segment_events=64))
        for i in range(10):
            with tel.tracer.span("step", i=i):
                pass
        tel.close()
        assert len(tel.tracer.events()) == 4  # in-memory capped
        spans = _spans(tmp_path)
        assert len(spans) == 10  # black box got them all
        assert spans[-1]["args"] == {"i": 9}


# ---------------------------------------------------------------------------
# kill -9 mid-training: the black box survives and the CLI reads it
# ---------------------------------------------------------------------------

FLIGHT_CHAOS_RUNNER = '''
import json, os, sys
sys.path.insert(0, {repo!r})
from determined_clone_tpu.utils.host_steering import steer_to_host_cpu
steer_to_host_cpu(8)
import jax
sys.path.insert(0, {testdir!r})
from test_fault_tolerance import DriftTrial, drift_config
from determined_clone_tpu import core
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.parallel import MeshSpec, make_mesh
from determined_clone_tpu.training import Trainer, TrialContext

cfg = ExperimentConfig.from_dict(drift_config({storage!r}, batches=24))
mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
with core.init(config=cfg, trial_id=1) as cctx:
    ctx = TrialContext(config=cfg, hparams={{}}, core=cctx, mesh=mesh)
    result = Trainer(DriftTrial(ctx)).fit()
print("COMPLETED", result["batches_trained"])
'''


@pytest.mark.slow
def test_kill9_leaves_readable_flight_ring(tmp_path):
    """A subprocess trial with DCT_FLIGHT_DIR set is hard-killed mid-run
    (os._exit via an `exit` fault: no atexit, no flushes — kill -9
    semantics). The flight ring on disk must still hold the final pre-kill
    train_dispatch spans, and `dct debug flight` must merge it into a
    Chrome trace that passes structural validation — the post-mortem
    acceptance criterion of the observability issue."""
    storage = tmp_path / "ckpts"
    storage.mkdir()
    flight_dir = tmp_path / "flight"
    script = tmp_path / "chaos_run.py"
    script.write_text(FLIGHT_CHAOS_RUNNER.format(
        repo=REPO, testdir=os.path.join(REPO, "tests"),
        storage=str(storage)))
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PALLAS_AXON_POOL_IPS": "",
        "DCT_FLIGHT_DIR": str(flight_dir),
        # die right after the 13th step completes: the spans for steps
        # 1-13 are already through the sink when the process vanishes
        "DCT_FAULT_PLAN": json.dumps({"rules": [
            {"point": "training.post_step", "action": "exit",
             "nth": 13, "exit_code": 137}]}),
    }
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 137, proc.stdout + proc.stderr
    assert "COMPLETED" not in proc.stdout

    # the ring survived the un-flushed death and holds the hot-loop spans
    summary = flight_summary(str(flight_dir))
    assert summary["segments"] >= 1
    dispatches = summary["span_names"].get("train_dispatch", 0)
    assert dispatches >= 10, summary["span_names"]

    trace = flight_to_chrome_trace(str(flight_dir))
    assert validate_chrome_trace(trace) == []
    assert any(e["name"] == "train_dispatch"
               for e in trace["traceEvents"])

    # the operator-facing path: `dct debug flight DIR -o trace.json`
    from determined_clone_tpu.cli.cli import main as cli_main
    out = tmp_path / "postmortem.json"
    rc = cli_main(["debug", "flight", str(flight_dir), "-o", str(out)])
    assert rc == 0
    written = json.loads(out.read_text())
    assert validate_chrome_trace(written) == []
    assert any(e["name"] == "train_dispatch"
               for e in written["traceEvents"])
