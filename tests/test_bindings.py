"""Generated bindings: schema drift gate, JSON mapping, live-master e2e.

≈ the reference's generated bindings tests: bindings regenerate cleanly from
proto (the "make check" drift gate over bindings/generate_bindings_py.py)
and the typed client speaks the master's REST gateway, including the
poll-stream emulation of streaming TrialLogs (api.proto:781).
"""
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from determined_clone_tpu.api import bindings as b

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"


def test_bindings_not_stale():
    """The checked-in bindings.py must match a fresh regeneration."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bindings" / "generate_bindings_py.py"),
         "--check"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr or r.stdout


def test_message_roundtrip_and_defaults():
    t = b.V1Trial.from_json({"id": 5, "hparams": {"lr": 0.1},
                             "state": "RUNNING", "has_metric": True,
                             "best_metric": 0.25, "restarts": 0,
                             "error": ""})
    assert t.id == 5 and t.hparams == {"lr": 0.1} and t.has_metric
    full = t.to_json()
    # explicit presence: server-sent zero-values round-trip...
    assert full["restarts"] == 0 and full["error"] == ""
    assert full["best_metric"] == 0.25
    # ...but unset fields stay unset (proto3 explicit presence)
    assert "units_done" not in full and b.V1Trial().to_json() == {}
    # explicit zero is expressible in requests (e.g. priority=0)
    req = b.V1CreateTaskRequest(type="shell", priority=0)
    assert req.to_json() == {"type": "shell", "priority": 0}
    # unset path params are caller bugs, not silent re-routes
    with pytest.raises(ValueError):
        b.get_experiment(None, b.V1GetExperimentRequest())
    # nested messages
    resp = b.V1GetExperimentResponse.from_json({
        "experiment": {"id": 1, "state": "RUNNING"},
        "trials": [{"id": 2}, {"id": 3}],
        "progress": 0.5,
    })
    assert resp.experiment.id == 1
    assert [t.id for t in resp.trials] == [2, 3]
    assert resp.progress == 0.5


def test_rpc_surface_matches_proto():
    """Every service RPC in the proto has a generated function."""
    src = (REPO / "proto" / "dct" / "api" / "v1" / "api.proto").read_text()
    import re

    rpcs = re.findall(r"rpc (\w+)\(", src)
    assert len(rpcs) >= 30
    from bindings.generate_bindings_py import snake

    for rpc in rpcs:
        assert hasattr(b, snake(rpc)), f"missing binding for {rpc}"


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("bindings")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            session.master_info()
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")
    yield session
    proc.kill()
    proc.wait(timeout=10)


def test_typed_calls_against_live_master(master):
    info = b.get_master(master, b.V1GetMasterRequest())
    assert info.version and info.cluster_name == "dct"

    login = b.login(master, b.V1LoginRequest(username="admin"))
    assert login.token and login.user.username == "admin"

    resp = b.create_experiment(master, b.V1CreateExperimentRequest(config={
        "name": "bindings-exp",
        "entrypoint": "x:Trial",
        "searcher": {"name": "custom", "metric": "loss"},
        "hyperparameters": {"lr": 0.1},
    }))
    exp = resp.experiment
    assert exp.id > 0 and exp.state == "RUNNING"

    detail = b.get_experiment(master, b.V1GetExperimentRequest(id=exp.id))
    assert detail.experiment.name == "bindings-exp"

    events = b.get_searcher_events(
        master, b.V1GetSearcherEventsRequest(id=exp.id, since=0))
    assert [e.type for e in events.events] == ["initial_operations"]

    out = b.post_searcher_operations(
        master, b.V1PostSearcherOperationsRequest(
            id=exp.id,
            ops=[b.V1SearcherOperation(type="shutdown", cancel=True)]))
    assert out.state == "CANCELED"

    killed = b.kill_experiment(master,
                               b.V1KillExperimentRequest(id=exp.id))
    assert killed.experiment.state == "CANCELED"


def test_rbac_and_jobqueue_bindings(master):
    """The round's new surfaces ride the generated client too."""
    roles = b.list_roles(master, b.V1ListRolesRequest())
    assert [r.name for r in roles.roles] == [
        "Viewer", "Editor", "WorkspaceAdmin", "ClusterAdmin"]

    g = b.create_group(master, b.V1CreateGroupRequest(name="binding-group"))
    assert g.group.id > 0
    a = b.assign_role(master, b.V1AssignRoleRequest(
        role="Editor", group_id=g.group.id))
    assert a.assignment.role == "Editor"
    listed = b.list_role_assignments(master,
                                     b.V1ListRoleAssignmentsRequest())
    assert any(x.id == a.assignment.id for x in listed.assignments)

    t1 = b.create_task(master, b.V1CreateTaskRequest(
        type="command", cmd=["echo", "1"], slots=1)).task
    t2 = b.create_task(master, b.V1CreateTaskRequest(
        type="command", cmd=["echo", "2"], slots=1)).task
    moved = b.move_job(master, b.V1MoveJobRequest(id=t2.id, ahead_of=t1.id))
    assert moved.job.queued_at < t1.queued_at
    prio = b.set_job_priority(master,
                              b.V1SetJobPriorityRequest(id=t1.id, priority=3))
    assert prio.job.priority == 3
    # allgather only accepts live (scheduled) gangs
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError) as err:
        b.all_gather(master, b.V1AllGatherRequest(
            id=t1.id, rank=0, round=0, data={"port": 99}))
    assert err.value.status == 409
    for t in (t1, t2):
        b.kill_task(master, b.V1KillTaskRequest(id=t.id))


def test_stream_task_logs_pages(master):
    task = b.create_task(master, b.V1CreateTaskRequest(
        type="shell", name="logstream")).task
    # no agent in this fixture: the task stays QUEUED, but its allocation
    # accepts shipped logs — enough to exercise the paging stream
    for i in range(25):
        master.request("POST", f"/api/v1/allocations/{task.id}/logs",
                       {"logs": [f"line-{i}"]})
    pages = list(b.get_task_logs(master, b.V1GetTaskLogsRequest(
        id=task.id, limit=10)))
    assert len(pages) == 3
    records = [rec for page in pages for rec in page.logs]
    assert len(records) == 25
    assert records[0].log == "line-0" and records[24].log == "line-24"
    assert all(r.allocation_id == task.id for r in records)
    # the session-level generator flattens the same stream
    flat = list(master.stream_task_logs(task.id, page_size=10))
    assert [r["log"] for r in flat] == [f"line-{i}" for i in range(25)]


def test_experiment_lifecycle_bindings(master):
    """pause/activate/archive/delete ride the generated client."""
    resp = b.create_experiment(master, b.V1CreateExperimentRequest(config={
        "name": "bindings-lifecycle", "entrypoint": "x:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
    }))
    eid = resp.experiment.id
    paused = b.pause_experiment(master, b.V1PauseExperimentRequest(id=eid))
    assert paused.experiment.state == "PAUSED"
    active = b.activate_experiment(master,
                                   b.V1ActivateExperimentRequest(id=eid))
    assert active.experiment.state == "RUNNING"
    b.kill_experiment(master, b.V1KillExperimentRequest(id=eid))
    archived = b.archive_experiment(master,
                                    b.V1ArchiveExperimentRequest(id=eid))
    assert archived.experiment.archived is True
    unarchived = b.unarchive_experiment(
        master, b.V1UnarchiveExperimentRequest(id=eid))
    assert unarchived.experiment.archived is False
    b.delete_experiment(master, b.V1DeleteExperimentRequest(id=eid))
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError):
        b.get_experiment(master, b.V1GetExperimentRequest(id=eid))


def test_round4_surface_bindings(master):
    """The round-4 proto growth (templates, webhooks, model registry
    depth, workspaces, user admin, operator surfaces, trial/allocation
    data planes) all round-trip through the generated client against a
    live master."""
    # templates
    b.set_template(master, b.V1SetTemplateRequest(
        name="bind-tpl", config={"max_restarts": 2}))
    tpls = b.list_templates(master, b.V1ListTemplatesRequest())
    assert "bind-tpl" in [t.name for t in tpls.templates]
    got = b.get_template(master, b.V1GetTemplateRequest(name="bind-tpl"))
    assert got.config["max_restarts"] == 2
    b.delete_template(master, b.V1DeleteTemplateRequest(name="bind-tpl"))
    assert "bind-tpl" not in [
        t.name for t in
        b.list_templates(master, b.V1ListTemplatesRequest()).templates]

    # webhooks
    wh = b.create_webhook(master, b.V1CreateWebhookRequest(
        url="http://127.0.0.1:1/hook", triggers=["COMPLETED"]))
    assert wh.webhook.id > 0
    assert wh.webhook.id in [
        w.id for w in
        b.list_webhooks(master, b.V1ListWebhooksRequest()).webhooks]
    b.delete_webhook(master, b.V1DeleteWebhookRequest(id=wh.webhook.id))

    # model registry depth
    b.create_model(master, b.V1CreateModelRequest(name="bind-model"))
    m = b.get_model(master, b.V1GetModelRequest(name="bind-model"))
    assert m.model.name == "bind-model"
    m = b.patch_model(master, b.V1PatchModelRequest(
        name="bind-model", description="patched"))
    assert m.model.description == "patched"
    b.archive_model(master, b.V1ArchiveModelRequest(name="bind-model"))
    b.unarchive_model(master, b.V1UnarchiveModelRequest(name="bind-model"))
    # a version needs a checkpoint reported through a trial
    resp = b.create_experiment(master, b.V1CreateExperimentRequest(config={
        "name": "bind-ckpt-exp", "entrypoint": "x:Y",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
        "hyperparameters": {},
    }))
    exp_id = resp.experiment.id
    deadline = time.time() + 30
    trial_id = None
    while time.time() < deadline and trial_id is None:
        det = b.get_experiment(master, b.V1GetExperimentRequest(id=exp_id))
        trial_id = det.trials[0].id if det.trials else None
        time.sleep(0.2)
    b.report_trial_checkpoint(master, b.V1ReportTrialCheckpointRequest(
        id=trial_id, uuid="bind-ck-1", metadata={"steps_completed": 1}))
    v = b.register_model_version(master, b.V1RegisterModelVersionRequest(
        name="bind-model", checkpoint_uuid="bind-ck-1",
        version_name="first"))
    assert v.version.version == 1 and v.version.name == "first"
    vs = b.list_model_versions(
        master, b.V1ListModelVersionsRequest(name="bind-model"))
    assert [x.version for x in vs.versions] == [1]
    ckpts = b.get_trial_checkpoints(
        master, b.V1GetTrialCheckpointsRequest(id=trial_id))
    assert "bind-ck-1" in [c.uuid for c in ckpts.checkpoints]
    b.delete_model_version(master, b.V1DeleteModelVersionRequest(
        name="bind-model", version=1))
    b.delete_model(master, b.V1DeleteModelRequest(name="bind-model"))

    # trial data plane: profiler + searcher ops
    b.report_trial_profiler(master, b.V1ReportTrialProfilerRequest(
        id=trial_id, samples=[{"cpu": 0.5}]))
    prof = b.get_trial_profiler(
        master, b.V1GetTrialProfilerRequest(id=trial_id, limit=10))
    assert prof.samples and prof.samples[-1]["cpu"] == 0.5
    op = b.get_searcher_operation(
        master, b.V1GetSearcherOperationRequest(id=trial_id))
    assert op.has_work and op.target_units > 0
    done = b.complete_searcher_operation(
        master, b.V1CompleteSearcherOperationRequest(
            id=trial_id, metric=0.1, units=op.target_units))
    assert done.trial.units_done == op.target_units

    # workspaces/projects depth
    ws = b.create_workspace(master, b.V1CreateWorkspaceRequest(
        name="bind-ws"))
    detail = b.get_workspace(master, b.V1GetWorkspaceRequest(
        id=ws.workspace.id))
    assert detail.workspace.name == "bind-ws"
    proj = b.create_project(master, b.V1CreateProjectRequest(
        id=ws.workspace.id, name="bind-proj"))
    assert proj.project.workspace_id == ws.workspace.id
    projs = b.list_workspace_projects(
        master, b.V1ListWorkspaceProjectsRequest(id=ws.workspace.id))
    assert "bind-proj" in [p.name for p in projs.projects]
    b.archive_workspace(master, b.V1ArchiveWorkspaceRequest(
        id=ws.workspace.id))
    out = b.unarchive_workspace(master, b.V1UnarchiveWorkspaceRequest(
        id=ws.workspace.id))
    assert not out.workspace.archived

    # user admin depth
    u = b.create_user(master, b.V1CreateUserRequest(
        username="bind-user", password="pw"))
    got_u = b.get_user(master, b.V1GetUserRequest(id=u.user.id))
    assert got_u.user.username == "bind-user"
    b.set_user_password(master, b.V1SetUserPasswordRequest(
        id=u.user.id, password="pw2"))
    deact = b.deactivate_user(master, b.V1DeactivateUserRequest(id=u.user.id))
    assert not deact.user.active
    act = b.activate_user(master, b.V1ActivateUserRequest(id=u.user.id))
    assert act.user.active

    # operator surfaces
    cfg = b.get_master_config(master, b.V1GetMasterConfigRequest())
    assert cfg.port == master.port and cfg.db in ("files", "sqlite")
    prov = b.get_provisioner_status(
        master, b.V1GetProvisionerStatusRequest())
    assert not prov.enabled  # fixture master runs without a provisioner
    # the fixture master has no agent daemon: register an artificial one
    # so pool occupancy and the drain controls have a target
    master.post("/api/v1/agents/register",
                {"id": "bind-agent", "slots": 4, "topology": "v5e-4",
                 "resource_pool": "default"})
    pools = b.list_resource_pools(
        master, b.V1ListResourcePoolsRequest())
    default = next(p for p in pools.resource_pools if p.is_default)
    assert default.slots_total >= 4 and default.scheduler
    agents = b.list_agents(master, b.V1ListAgentsRequest())
    aid = agents.agents[0].id
    one = b.get_agent(master, b.V1GetAgentRequest(id=aid))
    assert one.agent.id == aid
    off = b.disable_agent(master, b.V1DisableAgentRequest(id=aid))
    assert not off.agent.enabled
    # neither a heartbeat NOR a re-registration (agent restart / missed
    # heartbeat backoff) may undo the admin drain
    master.post(f"/api/v1/agents/{aid}/heartbeat", {})
    assert not b.get_agent(
        master, b.V1GetAgentRequest(id=aid)).agent.enabled
    master.post("/api/v1/agents/register",
                {"id": aid, "slots": 4, "topology": "v5e-4"})
    assert not b.get_agent(
        master, b.V1GetAgentRequest(id=aid)).agent.enabled
    on = b.enable_agent(master, b.V1EnableAgentRequest(id=aid))
    assert on.agent.enabled
    master.post("/api/v1/agents/register",
                {"id": aid, "slots": 4, "topology": "v5e-4"})
    assert b.get_agent(master, b.V1GetAgentRequest(id=aid)).agent.enabled

    # experiment context + allocation data plane
    ctx = b.get_experiment_context(
        master, b.V1GetExperimentContextRequest(id=exp_id))
    assert ctx.context == []  # created without context files
    alloc_id = f"trial-{trial_id}.0"
    rz = b.post_rendezvous(master, b.V1PostRendezvousRequest(
        id=alloc_id, rank=0, address="127.0.0.1:1"))
    assert rz.ready  # unscheduled fixture alloc: world_size stays 0
    rz2 = b.get_rendezvous(master, b.V1GetRendezvousRequest(id=alloc_id))
    assert rz2.ready and rz2.members == ["127.0.0.1:1"]
    pre = b.get_preempt(master, b.V1GetPreemptRequest(id=alloc_id))
    assert pre.preempt in (True, False)
    pr = b.register_proxy(master, b.V1RegisterProxyRequest(
        id=alloc_id, address="127.0.0.1:9"))
    assert pr.address == "127.0.0.1:9"
    b.post_task_logs(master, b.V1PostTaskLogsRequest(
        id=alloc_id, logs=["from-bindings"]))
    page = next(iter(b.get_task_logs(
        master, b.V1GetTaskLogsRequest(id=alloc_id, limit=10))))
    assert "from-bindings" in [r.log for r in page.logs]

    b.kill_experiment(master, b.V1KillExperimentRequest(id=exp_id))


def test_ts_bindings_not_stale_and_complete():
    """The WebUI's generated client (bindings.js + bindings.d.ts) must
    match a fresh regeneration and cover every RPC in the proto."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bindings" / "generate_bindings_ts.py"),
         "--check"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr or r.stdout

    import re

    src = (REPO / "proto" / "dct" / "api" / "v1" / "api.proto").read_text()
    rpcs = re.findall(r"rpc (\w+)\(", src)
    js = (REPO / "webui" / "bindings.js").read_text()
    dts = (REPO / "webui" / "bindings.d.ts").read_text()
    for rpc in rpcs:
        camel = rpc[0].lower() + rpc[1:]
        assert f"  {camel}(" in js, f"bindings.js missing {camel}"
        assert f"  {camel}(req?:" in dts, f"bindings.d.ts missing {camel}"
    # the webui loads the generated client and calls through it
    index = (REPO / "webui" / "index.html").read_text()
    assert "/ui/bindings.js" in index
    app = (REPO / "webui" / "app.js").read_text()
    assert "dctBindings(api)" in app
    # no hand-rolled fetches remain outside the transport wrapper
    raw_calls = [l for l in app.splitlines()
                 if 'api("' in l and "function api" not in l]
    assert raw_calls == [], raw_calls
