"""Generated bindings: schema drift gate, JSON mapping, live-master e2e.

≈ the reference's generated bindings tests: bindings regenerate cleanly from
proto (the "make check" drift gate over bindings/generate_bindings_py.py)
and the typed client speaks the master's REST gateway, including the
poll-stream emulation of streaming TrialLogs (api.proto:781).
"""
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from determined_clone_tpu.api import bindings as b

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"


def test_bindings_not_stale():
    """The checked-in bindings.py must match a fresh regeneration."""
    r = subprocess.run(
        [sys.executable, str(REPO / "bindings" / "generate_bindings_py.py"),
         "--check"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr or r.stdout


def test_message_roundtrip_and_defaults():
    t = b.V1Trial.from_json({"id": 5, "hparams": {"lr": 0.1},
                             "state": "RUNNING", "has_metric": True,
                             "best_metric": 0.25, "restarts": 0,
                             "error": ""})
    assert t.id == 5 and t.hparams == {"lr": 0.1} and t.has_metric
    full = t.to_json()
    # explicit presence: server-sent zero-values round-trip...
    assert full["restarts"] == 0 and full["error"] == ""
    assert full["best_metric"] == 0.25
    # ...but unset fields stay unset (proto3 explicit presence)
    assert "units_done" not in full and b.V1Trial().to_json() == {}
    # explicit zero is expressible in requests (e.g. priority=0)
    req = b.V1CreateTaskRequest(type="shell", priority=0)
    assert req.to_json() == {"type": "shell", "priority": 0}
    # unset path params are caller bugs, not silent re-routes
    with pytest.raises(ValueError):
        b.get_experiment(None, b.V1GetExperimentRequest())
    # nested messages
    resp = b.V1GetExperimentResponse.from_json({
        "experiment": {"id": 1, "state": "RUNNING"},
        "trials": [{"id": 2}, {"id": 3}],
        "progress": 0.5,
    })
    assert resp.experiment.id == 1
    assert [t.id for t in resp.trials] == [2, 3]
    assert resp.progress == 0.5


def test_rpc_surface_matches_proto():
    """Every service RPC in the proto has a generated function."""
    src = (REPO / "proto" / "dct" / "api" / "v1" / "api.proto").read_text()
    import re

    rpcs = re.findall(r"rpc (\w+)\(", src)
    assert len(rpcs) >= 30
    from bindings.generate_bindings_py import snake

    for rpc in rpcs:
        assert hasattr(b, snake(rpc)), f"missing binding for {rpc}"


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("bindings")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            session.master_info()
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")
    yield session
    proc.kill()
    proc.wait(timeout=10)


def test_typed_calls_against_live_master(master):
    info = b.get_master(master, b.V1GetMasterRequest())
    assert info.version and info.cluster_name == "dct"

    login = b.login(master, b.V1LoginRequest(username="admin"))
    assert login.token and login.user.username == "admin"

    resp = b.create_experiment(master, b.V1CreateExperimentRequest(config={
        "name": "bindings-exp",
        "entrypoint": "x:Trial",
        "searcher": {"name": "custom", "metric": "loss"},
        "hyperparameters": {"lr": 0.1},
    }))
    exp = resp.experiment
    assert exp.id > 0 and exp.state == "RUNNING"

    detail = b.get_experiment(master, b.V1GetExperimentRequest(id=exp.id))
    assert detail.experiment.name == "bindings-exp"

    events = b.get_searcher_events(
        master, b.V1GetSearcherEventsRequest(id=exp.id, since=0))
    assert [e.type for e in events.events] == ["initial_operations"]

    out = b.post_searcher_operations(
        master, b.V1PostSearcherOperationsRequest(
            id=exp.id,
            ops=[b.V1SearcherOperation(type="shutdown", cancel=True)]))
    assert out.state == "CANCELED"

    killed = b.kill_experiment(master,
                               b.V1KillExperimentRequest(id=exp.id))
    assert killed.experiment.state == "CANCELED"


def test_rbac_and_jobqueue_bindings(master):
    """The round's new surfaces ride the generated client too."""
    roles = b.list_roles(master, b.V1ListRolesRequest())
    assert [r.name for r in roles.roles] == [
        "Viewer", "Editor", "WorkspaceAdmin", "ClusterAdmin"]

    g = b.create_group(master, b.V1CreateGroupRequest(name="binding-group"))
    assert g.group.id > 0
    a = b.assign_role(master, b.V1AssignRoleRequest(
        role="Editor", group_id=g.group.id))
    assert a.assignment.role == "Editor"
    listed = b.list_role_assignments(master,
                                     b.V1ListRoleAssignmentsRequest())
    assert any(x.id == a.assignment.id for x in listed.assignments)

    t1 = b.create_task(master, b.V1CreateTaskRequest(
        type="command", cmd=["echo", "1"], slots=1)).task
    t2 = b.create_task(master, b.V1CreateTaskRequest(
        type="command", cmd=["echo", "2"], slots=1)).task
    moved = b.move_job(master, b.V1MoveJobRequest(id=t2.id, ahead_of=t1.id))
    assert moved.job.queued_at < t1.queued_at
    prio = b.set_job_priority(master,
                              b.V1SetJobPriorityRequest(id=t1.id, priority=3))
    assert prio.job.priority == 3
    # allgather only accepts live (scheduled) gangs
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError) as err:
        b.all_gather(master, b.V1AllGatherRequest(
            id=t1.id, rank=0, round=0, data={"port": 99}))
    assert err.value.status == 409
    for t in (t1, t2):
        b.kill_task(master, b.V1KillTaskRequest(id=t.id))


def test_stream_task_logs_pages(master):
    task = b.create_task(master, b.V1CreateTaskRequest(
        type="shell", name="logstream")).task
    # no agent in this fixture: the task stays QUEUED, but its allocation
    # accepts shipped logs — enough to exercise the paging stream
    for i in range(25):
        master.request("POST", f"/api/v1/allocations/{task.id}/logs",
                       {"logs": [f"line-{i}"]})
    pages = list(b.get_task_logs(master, b.V1GetTaskLogsRequest(
        id=task.id, limit=10)))
    assert len(pages) == 3
    records = [rec for page in pages for rec in page.logs]
    assert len(records) == 25
    assert records[0].log == "line-0" and records[24].log == "line-24"
    assert all(r.allocation_id == task.id for r in records)
    # the session-level generator flattens the same stream
    flat = list(master.stream_task_logs(task.id, page_size=10))
    assert [r["log"] for r in flat] == [f"line-{i}" for i in range(25)]


def test_experiment_lifecycle_bindings(master):
    """pause/activate/archive/delete ride the generated client."""
    resp = b.create_experiment(master, b.V1CreateExperimentRequest(config={
        "name": "bindings-lifecycle", "entrypoint": "x:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
    }))
    eid = resp.experiment.id
    paused = b.pause_experiment(master, b.V1PauseExperimentRequest(id=eid))
    assert paused.experiment.state == "PAUSED"
    active = b.activate_experiment(master,
                                   b.V1ActivateExperimentRequest(id=eid))
    assert active.experiment.state == "RUNNING"
    b.kill_experiment(master, b.V1KillExperimentRequest(id=eid))
    archived = b.archive_experiment(master,
                                    b.V1ArchiveExperimentRequest(id=eid))
    assert archived.experiment.archived is True
    unarchived = b.unarchive_experiment(
        master, b.V1UnarchiveExperimentRequest(id=eid))
    assert unarchived.experiment.archived is False
    b.delete_experiment(master, b.V1DeleteExperimentRequest(id=eid))
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError):
        b.get_experiment(master, b.V1GetExperimentRequest(id=eid))
