"""Serving-fleet surface (docs/serving.md "Replica fleets"): the
least-loaded router's selection/failover/exclusion contract on fake
ports, drain-protected scale-down that never drops an in-flight request,
blue-green rollout under load with bit-identical greedy outputs, the
queue-driven autoscaler's deterministic grow/shrink/cooldown ticks, the
aggregator's fleet rollup, the fleet HTTP front door, and the master
``serving`` gang-allocation lifecycle (skips when the C++ build is
unavailable)."""
import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from determined_clone_tpu.core._serialization import save_pytree
from determined_clone_tpu.models import gpt
from determined_clone_tpu.serving import (
    AutoscalePolicy,
    Autoscaler,
    AutoscaleSignals,
    BucketSpec,
    KVCacheConfig,
    LeastLoadedRouter,
    MasterLink,
    NoHealthyReplica,
    ServerOverloaded,
    ServingFleet,
)
from determined_clone_tpu.serving.http import (
    FleetHTTPServer,
    generate_over_http,
)
from determined_clone_tpu.telemetry import MetricsRegistry
from determined_clone_tpu.telemetry.aggregate import (
    ClusterMetricsAggregator,
    format_summary,
)
from tests.test_platform import build_binaries, start_master

CFG = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32, n_heads=4,
                    d_ff=64, max_seq_len=48, remat=False,
                    attention_impl="mha")
# the smallest ladder that still has a batch dimension: 2 batch buckets x
# 1 prefill bucket keeps per-test warmup to a handful of tiny compiles
BUCKETS = BucketSpec.build(2, 8)
CACHE = KVCacheConfig(num_blocks=16, block_size=8)
PROMPT = [1, 2, 3]  # == the rollout probe default, so probe output is a ref
MAX_NEW = 8


@pytest.fixture(scope="module")
def params():
    return gpt.init(jax.random.PRNGKey(0), CFG)


def naive_greedy(params, prompt, max_new):
    """Reference decode: full-context uncached forward every step."""
    toks = list(prompt)
    for _ in range(max_new):
        logits = gpt.apply(params, CFG, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def make_fleet(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("cache", CACHE)
    kw.setdefault("warmup", False)  # correctness tests compile on demand
    return ServingFleet(params, CFG, **kw)


# -- router units (fake ports — no engines, no jax) --------------------------

class FakePort:
    def __init__(self, rid, queue=0, free=16, fail=None):
        self.replica_id = rid
        self.queue = queue
        self.free = free
        self.fail = fail
        self.admit = True
        self.submitted = 0

    def admitting(self):
        return self.admit

    def load(self):
        return (self.queue, -self.free)

    def submit(self, prompt, max_new_tokens, *, eos_token_id=None,
               request_id=None):
        if self.fail is not None:
            raise self.fail
        self.submitted += 1

        class Handle:
            def result(self, timeout=None):
                return None

        return Handle()


def test_router_picks_least_queue_then_blocks_then_id():
    r = LeastLoadedRouter()
    a = FakePort("a", queue=3, free=16)
    b = FakePort("b", queue=1, free=2)
    c = FakePort("c", queue=1, free=9)
    for port in (a, b, c):
        r.add(port)
    # queue depth is the primary key ...
    assert r.pick().replica_id == "c"
    # ... free blocks break the queue tie (more is better) ...
    c.free = 2
    c2 = FakePort("a0", queue=1, free=2)
    r.add(c2)
    # ... and the id breaks a full tie, deterministically
    assert r.pick().replica_id == "a0"
    # a draining replica is never picked, whatever its load
    c2.admit = False
    b.admit = False
    c.admit = False
    assert r.pick().replica_id == "a"


def test_router_failover_excludes_and_counts_redispatch():
    now = [0.0]
    r = LeastLoadedRouter(exclude_cooldown_s=5.0, clock=lambda: now[0])
    bad = FakePort("bad", queue=0, fail=ServerOverloaded("queue full"))
    good = FakePort("good", queue=7)
    r.add(bad)
    r.add(good)
    # least-loaded would be bad; its 429 fails over to good in ONE call
    handle = r.submit(PROMPT, MAX_NEW)
    assert handle.replica_id == "good"
    assert good.submitted == 1
    assert r.excluded() == ["bad"]
    assert 'router_redispatch_total{reason="overloaded"} 1' \
        in r.registry.dump()
    # while excluded, traffic keeps landing on the healthy replica
    assert r.submit(PROMPT, MAX_NEW).replica_id == "good"
    # the cooldown expiring re-probes the failed replica
    now[0] = 6.0
    bad.fail = None
    assert r.excluded() == []
    assert r.submit(PROMPT, MAX_NEW).replica_id == "bad"


def test_router_connection_error_reason_label():
    r = LeastLoadedRouter()
    flaky = FakePort("flaky", queue=0, fail=ConnectionError("reset"))
    ok = FakePort("ok", queue=9)
    r.add(flaky)
    r.add(ok)
    assert r.submit(PROMPT, MAX_NEW).replica_id == "ok"
    assert 'router_redispatch_total{reason="connection"} 1' \
        in r.registry.dump()


def test_router_no_healthy_replica_raises():
    r = LeastLoadedRouter()
    with pytest.raises(NoHealthyReplica):
        r.submit(PROMPT, MAX_NEW, timeout=0.3)
    sick = FakePort("sick", fail=ServerOverloaded("full"))
    r.add(sick)
    with pytest.raises(NoHealthyReplica):
        r.submit(PROMPT, MAX_NEW, timeout=0.3)


def test_router_bad_request_not_failed_over():
    boom = FakePort("boom", fail=ValueError("empty prompt"))
    spare = FakePort("spare", queue=9)
    r = LeastLoadedRouter()
    r.add(boom)
    r.add(spare)
    # a malformed request is the client's fault: surfaced, not re-routed
    with pytest.raises(ValueError):
        r.submit(PROMPT, MAX_NEW)
    assert spare.submitted == 0
    assert r.excluded() == []


# -- fleet: routing parity, stats, aggregator rollup -------------------------

def test_fleet_parity_stats_and_rollup(params):
    """Both replicas serve, every routed output is bit-identical to the
    uncached reference, and the sampled per-replica registries roll up
    into the aggregator's fleet view (and its dct_fleet_* gauges)."""
    expected = naive_greedy(params, PROMPT, MAX_NEW)
    agg = ClusterMetricsAggregator()
    fleet = make_fleet(params, iteration_floor_s=0.05, aggregator=agg)
    try:
        fleet.scale_up(2)
        handles = [fleet.submit(PROMPT, MAX_NEW, timeout=60.0)
                   for _ in range(16)]
        results = [h.result(timeout=60.0) for h in handles]
        assert all(r.tokens == expected for r in results)
        # the burst queues deep enough that least-loaded MUST spread it
        assert {h.replica_id for h in handles} == set(fleet.replica_ids())

        st = fleet.stats()
        assert st.replicas == 2 and st.healthy == 2
        assert st.completed == 16 and st.rejected == 0
        assert st.tokens_generated == 16 * MAX_NEW
        assert st.max_p99_s > 0.0

        fleet.sample_telemetry()
        rollup = agg.serving_fleet_rollup()
        assert rollup is not None
        assert rollup["replicas"] == 2
        assert rollup["requests_completed"] == 16
        assert rollup["free_kv_blocks"] == 2 * CACHE.num_blocks
        assert rollup["max_replica_p99_s"] == pytest.approx(
            st.max_p99_s, rel=1e-6)
        dump = agg.dump()
        assert "dct_fleet_replicas 2" in dump
        assert "dct_fleet_requests_completed 16" in dump
        assert 'component="serving_replica_' in dump
        summary = agg.summary()
        assert summary["serving_fleet"]["replicas"] == 2
        assert "serving fleet: 2 replicas" in format_summary(summary)
    finally:
        fleet.close()


def test_scale_down_mid_burst_never_drops_requests(params):
    """The drain protocol: scaling down while a burst is in flight must
    complete every accepted request (on the right params) before the
    victim replica exits."""
    expected = naive_greedy(params, PROMPT, MAX_NEW)
    fleet = make_fleet(params, iteration_floor_s=0.02)
    try:
        fleet.scale_up(2)
        handles = [fleet.submit(PROMPT, MAX_NEW, timeout=60.0)
                   for _ in range(16)]
        # mid-burst: both replicas hold queued + running work right now
        assert fleet.stats().queue_depth > 0
        removed = fleet.scale_down(1, timeout=60.0)
        assert len(removed) == 1
        # the drain blocked until the victim was idle — nothing dropped
        results = [h.result(timeout=60.0) for h in handles]
        assert [r.tokens for r in results] == [expected] * 16
        assert fleet.stats().rejected == 0
        assert len(fleet.replica_ids()) == 1
        # the survivor keeps serving
        assert fleet.submit(PROMPT, MAX_NEW,
                            timeout=60.0).result(60.0).tokens == expected
    finally:
        fleet.close()


def test_blue_green_rollout_under_load_bit_identical(params):
    """Rollout mid-burst: zero failed requests, and every greedy output
    equals the old- or new-version reference bit for bit — a drain
    boundary means no sequence ever spans the param swap."""
    old_ref = naive_greedy(params, PROMPT, MAX_NEW)
    new_params = jax.tree_util.tree_map(lambda x: x * 3.0, params)
    new_ref = naive_greedy(new_params, PROMPT, MAX_NEW)
    assert old_ref != new_ref  # x3 provably changes the greedy stream

    fleet = make_fleet(params, iteration_floor_s=0.01)
    try:
        fleet.scale_up(2)
        box = {}

        def do_rollout():
            box["report"] = fleet.rollout(new_params,
                                          probe_tokens=MAX_NEW)

        roller = threading.Thread(target=do_rollout, name="test-rollout")
        handles = []
        for i in range(24):
            handles.append(fleet.submit(PROMPT, MAX_NEW, timeout=60.0))
            if i == 6:
                roller.start()
            time.sleep(0.005)  # the burst must span the rollout window
        results = [h.result(timeout=60.0) for h in handles]
        roller.join(60.0)
        assert not roller.is_alive()

        phases = {tuple(r.tokens) for r in results}
        assert phases <= {tuple(old_ref), tuple(new_ref)}
        assert tuple(old_ref) in phases  # traffic before the swap ...
        report = box["report"]
        assert report.order == sorted(fleet.replica_ids())
        assert report.probe_output == new_ref  # canary proven on new params
        assert set(report.drain_s) == set(report.order)
        assert report.duration_s > 0.0
        # ... and the fleet serves the new version afterwards
        assert fleet.submit(PROMPT, MAX_NEW,
                            timeout=60.0).result(60.0).tokens == new_ref
        assert fleet.stats().rejected == 0
    finally:
        fleet.close()


# -- autoscaler: deterministic ticks on injected signals ---------------------

class FakeFleet:
    def __init__(self, healthy=1):
        self.registry = MetricsRegistry()
        self.healthy = healthy
        self.ups = []
        self.downs = []

    def healthy_count(self):
        return self.healthy

    def scale_up(self, n):
        self.ups.append(n)
        self.healthy += n

    def scale_down(self, n, timeout=60.0):
        self.downs.append(n)
        self.healthy -= n


def test_autoscaler_grow_shrink_cooldown():
    fleet = FakeFleet(healthy=1)
    policy = AutoscalePolicy(min_replicas=1, max_replicas=3,
                             queue_high=8.0, p99_high_s=2.0,
                             breach_ticks=2, queue_low=0.5,
                             idle_ticks=2, cooldown_ticks=1)
    scaler = Autoscaler(fleet, policy)
    hot = AutoscaleSignals(healthy=1, queue_depth=20, p99_s=0.1)
    # sustained breach: hold (streak 1) → grow (streak 2) → cooldown hold
    assert scaler.tick(hot) == "hold"
    assert scaler.tick(hot) == "grow"
    assert fleet.ups == [1] and fleet.healthy == 2
    assert scaler.tick(hot) == "hold"  # cooldown eats this tick
    # a single calm tick resets the breach streak
    calm = AutoscaleSignals(healthy=2, queue_depth=4, p99_s=0.1)
    assert scaler.tick(hot) == "hold"
    assert scaler.tick(calm) == "hold"
    assert scaler.tick(hot) == "hold"
    assert fleet.ups == [1]
    # p99 breach alone also counts as congestion — the streak is shared
    # with the queue signal, so the hot tick above plus this one grows
    slow = AutoscaleSignals(healthy=2, queue_depth=0, p99_s=5.0)
    assert scaler.tick(slow) == "grow"
    assert fleet.healthy == 3
    assert scaler.tick(slow) == "hold"  # cooldown
    # at max_replicas a sustained breach holds instead of growing
    full = AutoscaleSignals(healthy=3, queue_depth=60, p99_s=9.0)
    assert scaler.tick(full) == "hold"
    assert scaler.tick(full) == "hold"
    assert fleet.ups == [1, 1]
    # idle: two quiet ticks shrink, through the drain-protected path
    idle = AutoscaleSignals(healthy=3, queue_depth=0, p99_s=0.0)
    assert scaler.tick(idle) == "hold"
    assert scaler.tick(idle) == "shrink"
    assert fleet.downs == [1] and fleet.healthy == 2
    assert scaler.tick(idle) == "hold"  # cooldown
    dump = scaler.registry.dump()
    assert "autoscale_grow_total 2" in dump
    assert "autoscale_shrink_total 1" in dump


def test_autoscaler_respects_min_replicas_and_dry_run():
    fleet = FakeFleet(healthy=1)
    policy = AutoscalePolicy(min_replicas=1, max_replicas=2,
                             breach_ticks=1, idle_ticks=1,
                             cooldown_ticks=0)
    scaler = Autoscaler(fleet, policy)
    idle = AutoscaleSignals(healthy=1, queue_depth=0, p99_s=0.0)
    # already at the floor: idle streaks never shrink below min
    assert scaler.tick(idle) == "hold"
    assert scaler.tick(idle) == "hold"
    assert fleet.downs == []
    dry = Autoscaler(FakeFleet(healthy=1), policy, dry_run=True)
    hot = AutoscaleSignals(healthy=1, queue_depth=50, p99_s=9.0)
    assert dry.tick(hot) == "grow"
    assert dry.fleet.ups == []  # decided, not applied


# -- HTTP front door ---------------------------------------------------------

def test_fleet_http_generate_scale_rollout(params, tmp_path):
    expected = naive_greedy(params, PROMPT, MAX_NEW)
    new_params = jax.tree_util.tree_map(lambda x: x * 3.0, params)
    new_ref = naive_greedy(new_params, PROMPT, MAX_NEW)
    ckpt = tmp_path / "v2"
    save_pytree(str(ckpt), new_params)

    fleet = make_fleet(params, iteration_floor_s=0.0)
    fleet.scale_up(1)
    try:
        with FleetHTTPServer(fleet) as srv:
            out = generate_over_http(srv.url, PROMPT, MAX_NEW)
            assert out["tokens"] == expected
            assert out["replica_id"] in fleet.replica_ids()

            def req(method, path, body=None):
                r = urllib.request.Request(
                    f"{srv.url}{path}",
                    data=(json.dumps(body).encode()
                          if body is not None else None),
                    method=method,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(r, timeout=60) as resp:
                    return json.loads(resp.read() or "{}")

            j = req("GET", "/v1/fleet")
            assert j["name"] == fleet.name
            assert [r["state"] for r in j["replicas"]] == ["healthy"]

            j = req("POST", "/v1/scale", {"replicas": 2})
            assert len(j["replicas"]) == 2

            j = req("POST", "/v1/rollout", {"checkpoint": str(ckpt)})
            assert j["probe_output"] == new_ref
            assert sorted(j["drain_s"]) == sorted(fleet.replica_ids())
            assert generate_over_http(srv.url, PROMPT,
                                      MAX_NEW)["tokens"] == new_ref

            with urllib.request.urlopen(f"{srv.url}/metrics",
                                        timeout=30) as resp:
                text = resp.read().decode()
            assert "router_requests_total" in text
            assert "dct_fleet_replicas 2" in text

            with pytest.raises(urllib.error.HTTPError) as e:
                req("POST", "/v1/generate", {"prompt": "not-a-list"})
            assert e.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as e:
                req("POST", "/v1/rollout", {})
            assert e.value.code == 400
    finally:
        fleet.close()


# -- master integration: the `serving` gang allocation type ------------------

def master_req(port, method, path, body=None):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return json.loads(resp.read() or "{}")


def test_master_serving_gang_lifecycle(params, tmp_path):
    """Replicas ride real master allocations: the fleet shows up in
    /api/v1/serving/fleets with running gangs, sched telemetry carries
    the serving families, master-driven scale-down drains locally, and
    the kill reclaims every slot."""
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    expected = naive_greedy(params, PROMPT, MAX_NEW)
    proc, _session, port = start_master(tmp_path)
    fleet = make_fleet(params, name="itest", iteration_floor_s=0.0)
    link = None
    try:
        link = MasterLink(fleet, port, replicas=2)
        link.wait_replicas(2, timeout=60.0)

        fleets = master_req(port, "GET", "/api/v1/serving/fleets")["fleets"]
        mine = next(f for f in fleets if f["name"] == "itest")
        assert mine["running"] == 2 and mine["queued"] == 0
        states = [r["state"] for r in mine["replicas"]]
        assert states.count("RUNNING") == 2

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            text = resp.read().decode()
        for fam in ("dct_master_sched_serving_submitted_total",
                    "dct_master_sched_serving_running_total",
                    "dct_master_sched_serving_completed_total"):
            assert fam in text
        assert "dct_master_sched_serving_submitted_total 2" in text

        handles = [fleet.submit(PROMPT, MAX_NEW, timeout=60.0)
                   for _ in range(4)]
        assert all(h.result(60.0).tokens == expected for h in handles)

        # master-driven scale-down: the kill command drains locally
        link.scale(1)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if len(fleet.replica_ids()) == 1:
                break
            time.sleep(0.1)
        assert len(fleet.replica_ids()) == 1
        assert fleet.stats().rejected == 0

        link.close(kill_fleet=True)
        link = None
        mine = next(
            f for f in master_req(port, "GET",
                                  "/api/v1/serving/fleets")["fleets"]
            if f["name"] == "itest")
        assert mine["running"] == 0
    finally:
        if link is not None:
            link.close(kill_fleet=True)
        fleet.close()
        proc.kill()
        proc.wait(timeout=10)
