"""RBAC: roles, user groups, workspace-scoped assignments, enforcement.

Drives a C++ master started with --auth-required --rbac over REST,
≈ the reference's e2e_tests/tests/cluster/test_rbac.py against
master/internal/rbac + usergroup. Role model: a strict hierarchy
Viewer < Editor < WorkspaceAdmin < ClusterAdmin, assignable to users or
groups at global scope or per-workspace.
"""
import time
from pathlib import Path

import pytest

from tests.test_platform import build_binaries, start_master

from determined_clone_tpu.api.client import MasterError, MasterSession

REPO = Path(__file__).resolve().parent.parent


def login_as(master, username, password=""):
    s = MasterSession("127.0.0.1", master["port"], timeout=10, retries=2)
    s.login(username, password)
    return s


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("rbac")
    proc, session, port = start_master(tmp, "--auth-required", "--rbac")
    session.login("admin")
    yield {"session": session, "tmp": tmp, "port": port, "proc": proc}
    proc.kill()
    proc.wait(timeout=10)


def test_roles_are_static_hierarchy(master):
    roles = {r["name"]: r["rank"] for r in master["session"].list_roles()}
    assert roles == {"Viewer": 1, "Editor": 2, "WorkspaceAdmin": 3,
                     "ClusterAdmin": 4}


def test_admin_flag_is_cluster_admin(master):
    me = master["session"].my_permissions()
    assert me["role"] == "ClusterAdmin" and me["rank"] == 4
    assert me["enforced"] is True


def test_unassigned_user_cannot_mutate(master):
    admin = master["session"]
    admin.create_user("nobody", "pw")
    nobody = login_as(master, "nobody", "pw")
    assert nobody.my_permissions()["rank"] == 0
    with pytest.raises(MasterError) as err:
        nobody.create_experiment({"name": "x", "entrypoint": "x:Y"})
    assert err.value.status == 403
    with pytest.raises(MasterError) as err:
        nobody.create_workspace("nope")
    assert err.value.status == 403
    # reads remain session-gated only (any authenticated user)
    assert isinstance(nobody.list_experiments(), list)


def test_workspace_scoped_editor_via_group(master):
    admin = master["session"]
    ws = admin.create_workspace("ml-team")
    alice = admin.create_user("alice", "pw")
    group = admin.create_group("ml-editors", user_ids=[alice["id"]])
    admin.assign_role("Editor", group_id=group["id"], workspace_id=ws["id"])

    s = login_as(master, "alice", "pw")
    assert s.my_permissions(ws["id"])["role"] == "Editor"
    assert s.my_permissions()["rank"] == 0  # scope does not leak globally

    # can create experiments in ml-team...
    exp = s.create_experiment({
        "name": "ok", "entrypoint": "x:Y", "workspace": "ml-team",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
    })
    assert exp["workspace"] == "ml-team"
    s.kill_experiment(exp["id"])  # Editor can kill in-scope

    # ...but not in Uncategorized (different scope)
    with pytest.raises(MasterError) as err:
        s.create_experiment({"name": "no", "entrypoint": "x:Y"})
    assert err.value.status == 403

    # removing alice from the group revokes the grant
    admin.update_group_members(group["id"], remove=[alice["id"]])
    with pytest.raises(MasterError) as err:
        s.create_experiment({"name": "no2", "entrypoint": "x:Y",
                             "workspace": "ml-team"})
    assert err.value.status == 403
    admin.update_group_members(group["id"], add=[alice["id"]])


def test_editor_cannot_admin_workspace(master):
    admin = master["session"]
    ws_id = next(w["id"] for w in admin.list_workspaces()
                 if w["name"] == "ml-team")
    alice = login_as(master, "alice", "pw")
    # archive needs WorkspaceAdmin
    with pytest.raises(MasterError) as err:
        alice.post(f"/api/v1/workspaces/{ws_id}/archive")
    assert err.value.status == 403
    admin.assign_role("WorkspaceAdmin", user_id=[
        u["id"] for u in admin.list_users() if u["username"] == "alice"][0],
        workspace_id=ws_id)
    alice.post(f"/api/v1/workspaces/{ws_id}/archive")
    alice.post(f"/api/v1/workspaces/{ws_id}/unarchive")


def test_global_viewer_cannot_create(master):
    admin = master["session"]
    bob = admin.create_user("bob", "pw")
    admin.assign_role("Viewer", user_id=bob["id"])
    s = login_as(master, "bob", "pw")
    assert s.my_permissions()["role"] == "Viewer"
    with pytest.raises(MasterError) as err:
        s.create_model("m-bob")
    assert err.value.status == 403


def test_only_cluster_admin_manages_assignments(master):
    alice = login_as(master, "alice", "pw")
    with pytest.raises(MasterError) as err:
        alice.assign_role("Editor", user_id=1)
    assert err.value.status == 403
    with pytest.raises(MasterError) as err:
        alice.create_group("sneaky")
    assert err.value.status == 403


def test_ntsc_tasks_are_gated(master):
    admin = master["session"]
    ed = admin.create_user("ed", "pw")
    admin.assign_role("Editor", user_id=ed["id"])  # global scope

    nobody = login_as(master, "nobody", "pw")
    with pytest.raises(MasterError) as err:
        nobody.create_task("command", cmd=["echo", "hi"])
    assert err.value.status == 403

    s = login_as(master, "ed", "pw")
    task = s.create_task("command", cmd=["echo", "hi"], owner="ed")
    # a roleless user cannot kill someone else's task...
    with pytest.raises(MasterError) as err:
        nobody.kill_task(task["id"])
    assert err.value.status == 403
    # ...but the owner can, even without a global role on that route
    s.kill_task(task["id"])


def test_role_granted_cluster_admin_manages_users(master):
    admin = master["session"]
    root2 = admin.create_user("root2", "pw")
    admin.assign_role("ClusterAdmin", user_id=root2["id"])
    s = login_as(master, "root2", "pw")
    made = s.create_user("made-by-root2", "pw")
    assert made["username"] == "made-by-root2"
    g = s.create_group("root2-group")
    s.delete_group(g["id"])


def test_member_add_is_atomic(master):
    admin = master["session"]
    g = admin.create_group("atomic")
    uid = next(u["id"] for u in admin.list_users()
               if u["username"] == "nobody")
    with pytest.raises(MasterError) as err:
        admin.update_group_members(g["id"], add=[uid, 999999])
    assert err.value.status == 400
    # the valid id must NOT have been applied by the failed request
    assert admin.list_groups()[-1]["user_ids"] == [] or not any(
        grp["id"] == g["id"] and uid in grp["user_ids"]
        for grp in admin.list_groups())
    admin.delete_group(g["id"])


def test_assignment_validation(master):
    admin = master["session"]
    with pytest.raises(MasterError):
        admin.assign_role("NotARole", user_id=1)
    with pytest.raises(MasterError):
        admin.assign_role("Editor")  # no principal
    with pytest.raises(MasterError):
        admin.assign_role("Editor", user_id=1, group_id=1)  # both
    with pytest.raises(MasterError):
        admin.assign_role("ClusterAdmin", user_id=1, workspace_id=1)
    with pytest.raises(MasterError):
        admin.assign_role("Editor", user_id=999999)
    # exact duplicates are rejected — deleting one of two identical rows
    # would leave the grant silently active
    dup = admin.assign_role("Viewer", user_id=1)
    with pytest.raises(MasterError) as err:
        admin.assign_role("Viewer", user_id=1)
    assert "already exists" in str(err.value)
    admin.remove_role_assignment(dup["id"])


def test_deleting_group_revokes_roles(master):
    admin = master["session"]
    carol = admin.create_user("carol", "pw")
    g = admin.create_group("temps", user_ids=[carol["id"]])
    admin.assign_role("Editor", group_id=g["id"])
    s = login_as(master, "carol", "pw")
    assert s.my_permissions()["role"] == "Editor"
    admin.delete_group(g["id"])
    assert s.my_permissions()["rank"] == 0
    assert not any(a["group_id"] == g["id"]
                   for a in admin.list_role_assignments())


def test_workspace_delete_revokes_scoped_assignments(master):
    admin = master["session"]
    ws = admin.create_workspace("ephemeral")
    dave = admin.create_user("dave", "pw")
    a = admin.assign_role("Editor", user_id=dave["id"],
                          workspace_id=ws["id"])
    admin.request("DELETE", f"/api/v1/workspaces/{ws['id']}")
    assert not any(x["id"] == a["id"]
                   for x in admin.list_role_assignments())


def test_rbac_state_survives_restart(master):
    admin = master["session"]
    assignments_before = admin.list_role_assignments()
    groups_before = admin.list_groups()
    assert assignments_before and groups_before

    master["proc"].terminate()
    master["proc"].wait(timeout=10)
    proc, session, port = start_master(
        master["tmp"], "--auth-required", "--rbac")
    # replace the fixture's handles so later tests (and teardown) see the
    # live master, not the one we just terminated
    master.update(proc=proc, session=session, port=port)
    session.login("admin")
    assert session.list_role_assignments() == assignments_before
    assert session.list_groups() == groups_before
    # enforcement still live for a re-logged-in unassigned user
    s = MasterSession("127.0.0.1", port, timeout=10, retries=2)
    s.login("nobody", "pw")
    with pytest.raises(MasterError) as err:
        s.create_experiment({"name": "x", "entrypoint": "x:Y"})
    assert err.value.status == 403


def test_assignments_inert_without_rbac_flag(master):
    """Role-granted ClusterAdmin must not unlock the admin surface when the
    master restarts without --rbac (assignments persist but are inert)."""
    admin = master["session"]
    eve = admin.create_user("eve", "pw")
    admin.assign_role("ClusterAdmin", user_id=eve["id"])

    master["proc"].terminate()
    master["proc"].wait(timeout=10)
    proc, session, port = start_master(master["tmp"], "--auth-required")
    master.update(proc=proc, session=session, port=port)

    s = MasterSession("127.0.0.1", port, timeout=10, retries=2)
    s.login("eve", "pw")
    assert s.my_permissions()["enforced"] is False
    with pytest.raises(MasterError) as err:
        s.create_user("eve-minion", "pw")
    assert err.value.status == 403
    # the real admin flag still works
    session.login("admin")
    assert session.create_user("by-admin", "pw")["username"] == "by-admin"
