"""Autotune (dsat analogue): mesh-candidate search over measured throughput.

≈ the reference's dsat tests (search over DS configs driven by profile
metrics, _dsat_search_method.py) re-keyed to mesh factorizations.
"""
import json

import jax
import pytest


def test_mesh_candidates_enumeration():
    from determined_clone_tpu.autotune import mesh_candidates

    cands = mesh_candidates(8, ("dp", "fsdp", "tp"))
    # every candidate multiplies out to 8
    for c in cands:
        prod = 1
        for v in c.values():
            prod *= v
        assert prod == 8
    # dp-heavy first
    assert cands[0] == {"dp": 8, "fsdp": 1, "tp": 1}
    # all distinct
    assert len({tuple(sorted(c.items())) for c in cands}) == len(cands)
    # cap respected
    assert len(mesh_candidates(8, ("dp", "tp"), max_candidates=2)) == 2


def test_autotune_ranks_and_prunes():
    from determined_clone_tpu.autotune import autotune

    calls = []

    def measure(mesh, remat, batch):
        calls.append((tuple(sorted(mesh.items())), remat, batch))
        if mesh.get("tp", 1) == 4:
            raise RuntimeError("OOM: tp=4 infeasible")
        # pretend pure dp is fastest, fsdp slower, remat slower
        score = 100.0 * mesh.get("dp", 1) / (1 + mesh.get("fsdp", 1))
        return score * (0.9 if remat else 1.0)

    results = autotune(measure, 4, axes=("dp", "fsdp", "tp"),
                       remat_options=(False,), max_trials=32,
                       early_stop_after=32)
    assert results[0].feasible
    assert results[0].mesh == {"dp": 4, "fsdp": 1, "tp": 1}
    infeasible = [r for r in results if not r.feasible]
    assert infeasible and all("OOM" in r.error for r in infeasible)
    # ranked descending among feasible
    feas = [r.samples_per_sec for r in results if r.feasible]
    assert feas == sorted(feas, reverse=True)


def test_autotune_early_stop():
    from determined_clone_tpu.autotune import autotune

    n_calls = [0]

    def measure(mesh, remat, batch):
        n_calls[0] += 1
        return 1.0  # never improves after the first

    autotune(measure, 8, axes=("dp", "fsdp", "tp"), remat_options=(False,),
             max_trials=100, early_stop_after=3)
    # 1 best + 3 non-improving = stop
    assert n_calls[0] == 4


def test_autotune_real_gpt_on_cpu_mesh():
    """End-to-end local autotune over the virtual 8-device CPU mesh: real
    jitted sharded train steps per candidate."""
    from determined_clone_tpu.autotune import autotune
    from determined_clone_tpu.autotune.gpt_bench import make_gpt_measure

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")

    measure = make_gpt_measure(seq_len=32, warmup=1, steps=2)
    results = autotune(measure, 4, axes=("dp", "tp"),
                       remat_options=(True,), batch_options=(2,),
                       max_trials=3, early_stop_after=3)
    feasible = [r for r in results if r.feasible]
    assert feasible, [r.error for r in results]
    assert all(r.samples_per_sec > 0 for r in feasible)


def test_make_autotune_experiment_config():
    from determined_clone_tpu.autotune import make_autotune_experiment_config
    from determined_clone_tpu.config.experiment import ExperimentConfig

    base = {
        "name": "gpt-run",
        "entrypoint": "model_def:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 100}},
        "hyperparameters": {"lr": 0.001},
    }
    cfg = make_autotune_experiment_config(base, 8, axes=("dp", "fsdp", "tp"),
                                          max_candidates=6)
    assert cfg["name"] == "gpt-run-autotune"
    assert cfg["searcher"]["name"] == "grid"
    assert cfg["searcher"]["metric"] == "samples_per_second"
    assert cfg["searcher"]["smaller_is_better"] is False
    assert cfg["resources"]["slots_per_trial"] == 8
    meshes = [json.loads(v) for v in cfg["hyperparameters"]["mesh_json"]["vals"]]
    assert len(meshes) == 6
    for m in meshes:
        prod = 1
        for v in m.values():
            prod *= v
        assert prod == 8
    # base config untouched
    assert base["searcher"]["name"] == "single"
    # and the generated config validates + grid-expands client-side
    parsed = ExperimentConfig.from_dict(cfg)
    assert parsed.searcher.name == "grid"
