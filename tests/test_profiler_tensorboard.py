"""Profiler + TensorBoard subsystem.

Unit tier: tfevents writer/reader round trip (CRC-verified), profiler
sampling/batching against a fake session (≈ harness/tests profiler tests).
E2E tier: experiment with profiling enabled → samples land on the master;
tfevents uploaded to storage; `det tensorboard` task serves parsed scalars
through the master proxy.
"""
import json
import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"


# ---------------------------------------------------------------------------
# tfevents unit tests
# ---------------------------------------------------------------------------

def test_tfevents_round_trip(tmp_path):
    from determined_clone_tpu.tensorboard import (
        EventFileWriter,
        read_tfevents,
    )

    w = EventFileWriter(str(tmp_path))
    w.add_scalar("loss", 0.5, 1)
    w.add_scalar("loss", 0.25, 2)
    w.add_scalar("acc", 0.9, 2)
    w.close()

    events = list(read_tfevents(w.path))
    # first record is the file_version header (no scalars)
    scalars = [e for e in events if e["scalars"]]
    assert len(scalars) == 3
    assert scalars[0]["scalars"] == {"loss": 0.5}
    assert scalars[0]["step"] == 1
    assert scalars[2]["scalars"]["acc"] == pytest.approx(0.9)
    assert all(e["wall_time"] > 0 for e in scalars)


def test_tfevents_crc_detects_corruption(tmp_path):
    from determined_clone_tpu.tensorboard import (
        EventFileWriter,
        read_tfevents,
    )

    w = EventFileWriter(str(tmp_path))
    w.add_scalar("x", 1.0, 1)
    w.close()
    blob = bytearray(Path(w.path).read_bytes())
    blob[-6] ^= 0xFF  # flip a payload byte
    Path(w.path).write_bytes(bytes(blob))
    with pytest.raises(ValueError):
        list(read_tfevents(w.path))


def test_crc32c_known_vectors():
    from determined_clone_tpu.tensorboard._tfevents import crc32c

    # RFC 3720 test vectors
    assert crc32c(b"") == 0x0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA


def test_tensorboard_manager_sync(tmp_path):
    from determined_clone_tpu.tensorboard import (
        TensorboardManager,
        fetch_trial_events,
        read_tfevents,
    )

    storage_raw = {"type": "shared_fs", "host_path": str(tmp_path / "store")}
    mgr = TensorboardManager.from_config(
        storage_raw, 7, 3, str(tmp_path / "logs"))
    mgr.add_scalars("training", {"loss": 1.0, "skipme": "not-a-number"}, 1)
    mgr.add_scalars("training", {"loss": 0.5}, 2)
    mgr.sync()
    mgr.close()

    fetched = fetch_trial_events(storage_raw, 7, 3, str(tmp_path / "dl"))
    assert len(fetched) == 1
    series = [e["scalars"] for e in read_tfevents(fetched[0]) if e["scalars"]]
    assert series == [{"training/loss": 1.0}, {"training/loss": 0.5}]

    # unknown trial → empty, not an exception
    assert fetch_trial_events(storage_raw, 7, 999, str(tmp_path / "dl2")) == []


# ---------------------------------------------------------------------------
# profiler unit tests
# ---------------------------------------------------------------------------

class FakeSession:
    def __init__(self):
        self.posts = []

    def post(self, path, body, retryable=False):
        self.posts.append((path, body))
        return {}


def test_profiler_collects_and_flushes():
    from determined_clone_tpu.profiler import ProfilerAgent

    session = FakeSession()
    prof = ProfilerAgent(session, 42, enabled=True, sample_system=False)
    prof.start()
    prof.record_batch_timing(10, dataloading_s=0.1, compute_s=0.9)
    prof.record({"group": "system", "cpu_util_pct": 50.0, "time": 1.0})
    prof.stop()

    assert session.posts
    path, body = session.posts[0]
    assert path == "/api/v1/trials/42/profiler"
    groups = {s["group"] for s in body["samples"]}
    assert groups == {"timing", "system"}
    timing = [s for s in body["samples"] if s["group"] == "timing"][0]
    assert timing["batches_trained"] == 10
    assert timing["compute_s"] == pytest.approx(0.9)


def test_profiler_disabled_is_inert():
    from determined_clone_tpu.profiler import ProfilerAgent

    session = FakeSession()
    prof = ProfilerAgent(session, 1, enabled=False)
    prof.start()
    prof.record({"group": "system"})
    prof.stop()
    assert session.posts == []


def test_profiler_system_sampler_produces_metrics():
    from determined_clone_tpu.profiler import ProfilerAgent, SystemMetricsThread

    session = FakeSession()
    prof = ProfilerAgent(session, 1, enabled=True, sample_system=False)
    sampler = SystemMetricsThread(prof)
    sampler.sample_once()
    time.sleep(0.05)
    sampler.sample_once()  # second sample has cpu deltas
    prof.flush()
    samples = [s for _, b in session.posts for s in b["samples"]]
    assert samples
    assert any("memory_used_gb" in s for s in samples)
    assert any("cpu_util_pct" in s for s in samples)


def test_profiler_from_config_gating():
    from determined_clone_tpu.profiler import from_config

    assert from_config(FakeSession(), 1, {}).enabled is False
    assert from_config(
        FakeSession(), 1, {"profiling": {"enabled": True}}).enabled is True


# ---------------------------------------------------------------------------
# e2e: profiler samples + tensorboard through a live cluster
# ---------------------------------------------------------------------------

TRIAL_MODULE = '''
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training import JaxTrial


class Trial(JaxTrial):
    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.2)

    def loss(self, params, batch, rng):
        return (params["w"] - 2.0) ** 2, {}

    def training_data(self):
        for _ in range(64):
            yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return [np.zeros((2, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 2
'''


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("proftb")
    workdir = tmp / "agent-work"
    workdir.mkdir()
    (workdir / "model_def.py").write_text(TRIAL_MODULE)

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "prof-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


def wait_for(predicate, timeout=120, interval=0.5, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def test_profiling_and_tensorboard_e2e(cluster):
    session = cluster["session"]
    exp = session.create_experiment({
        "name": "prof-exp",
        "entrypoint": "model_def:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 6}},
        "resources": {"slots_per_trial": 1},
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(cluster["tmp"] / "ckpts")},
        "hyperparameters": {},
        "profiling": {"enabled": True},
        "max_restarts": 0,
    })
    wait_for(
        lambda: session.get_experiment(exp["id"])["experiment"]["state"]
        == "COMPLETED",
        desc="experiment completion",
    )
    trial_id = session.get_experiment(exp["id"])["trials"][0]["id"]

    # profiler samples reached the master: timing + system groups
    samples = wait_for(
        lambda: session.trial_profiler_samples(trial_id) or None,
        desc="profiler samples", timeout=30,
    )
    groups = {s.get("group") for s in samples}
    assert "timing" in groups
    timing = [s for s in samples if s.get("group") == "timing"]
    assert all("compute_s" in s and "dataloading_s" in s for s in timing)

    # tfevents shipped to checkpoint storage
    from determined_clone_tpu.tensorboard import (
        fetch_trial_events,
        read_tfevents,
    )

    storage_raw = {"type": "shared_fs",
                   "host_path": str(cluster["tmp"] / "ckpts")}
    files = fetch_trial_events(storage_raw, exp["id"], trial_id,
                               str(cluster["tmp"] / "tb-dl"))
    assert files, "no tfevents uploaded"
    tags = set()
    for path in files:
        for event in read_tfevents(path):
            tags.update(event["scalars"])
    assert "training/loss" in tags
    assert "validation/loss" in tags

    # tensorboard task serves parsed scalars through the proxy
    task = session.create_task("tensorboard", name="tb-e2e",
                               experiment_ids=[exp["id"]])
    wait_for(
        lambda: (lambda t: t if t["state"] == "RUNNING" and
                 t["proxy_address"] else None)(session.get_task(task["id"])),
        desc="tb task proxied", timeout=60,
    )
    data = session.proxy(task["id"], "/scalars")
    trial_data = data["experiments"][str(exp["id"])]["trials"][str(trial_id)]
    assert "training/loss" in trial_data["scalars"]
    assert trial_data["files"]
    session.kill_task(task["id"])
