"""End-to-end experiment orchestration: searcher ops drive real (tiny)
training runs with pause/resume via checkpoints."""
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.experiment import LocalExperimentRunner
from determined_clone_tpu.parallel import MeshSpec, make_mesh
from determined_clone_tpu.training import JaxTrial


class QuadraticTrial(JaxTrial):
    """loss = (w - lr*10)^2 + lr — optimum depends on the lr hparam, so the
    searcher has signal: smaller lr ends with smaller final loss."""

    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.3)

    def loss(self, params, batch, rng):
        lr = self.context.get_hparam("lr", 0.5)
        loss = (params["w"] - 1.0) ** 2 + lr
        return loss, {}

    def training_data(self):
        for _ in range(128):
            yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return [np.zeros((2, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 2


def base_config(tmp_path, searcher):
    return ExperimentConfig.from_dict({
        "searcher": searcher,
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs", "host_path": str(tmp_path)},
        "hyperparameters": {"lr": {"type": "double", "minval": 0.1,
                                   "maxval": 1.0}},
        "max_restarts": 1,
    })


def test_random_search_end_to_end(tmp_path):
    cfg = base_config(tmp_path, {
        "name": "random", "metric": "loss", "max_trials": 3,
        "max_length": {"batches": 4}, "max_concurrent_trials": 2,
    })
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    runner = LocalExperimentRunner(cfg, QuadraticTrial,
                                   storage_path=str(tmp_path), mesh=mesh)
    result = runner.run()
    assert result.shutdown
    assert result.n_trials == 3
    assert all(t.state == "completed" for t in result.trials.values())
    # best trial should be the one with smallest lr (loss floor = lr)
    lrs = {rid: t.hparams["lr"] for rid, t in result.trials.items()}
    assert result.best_trial.request_id == min(lrs, key=lrs.get)
    # experiment snapshot written (crash consistency)
    assert os.path.exists(tmp_path / "experiment_snapshot.json")
    # per-trial metrics recorded
    assert os.path.exists(result.best_trial.metrics_path)


def test_asha_pauses_and_promotes_via_checkpoints(tmp_path):
    cfg = base_config(tmp_path, {
        "name": "asha", "metric": "loss", "max_trials": 6,
        "num_rungs": 2, "divisor": 3, "max_length": {"batches": 6},
        "max_concurrent_trials": 6,
    })
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    runner = LocalExperimentRunner(cfg, QuadraticTrial,
                                   storage_path=str(tmp_path), mesh=mesh)
    result = runner.run()
    assert result.shutdown
    assert result.n_trials == 6
    units = sorted(t.units_done for t in result.trials.values())
    assert units[0] == 2          # rung 0 = 6 / 3
    assert units[-1] == 6         # someone reached the top rung
    promoted = [t for t in result.trials.values() if t.units_done == 6]
    # promoted trials resumed from their rung-0 checkpoint
    assert all(t.latest_checkpoint for t in promoted)


class FlakyTrial(QuadraticTrial):
    """Fails on first attempt, succeeds after restart (reference fixture
    style: e2e failure-injection, managed_cluster.py)."""

    _failed = {}

    def training_data(self):
        marker = self.context.core  # one failure per core ctx
        if not FlakyTrial._failed.get("done"):
            FlakyTrial._failed["done"] = True
            raise RuntimeError("injected failure")
        return super().training_data()


def test_max_restarts_recovers(tmp_path):
    FlakyTrial._failed = {}
    cfg = base_config(tmp_path, {
        "name": "single", "metric": "loss", "max_length": {"batches": 4},
    })
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    runner = LocalExperimentRunner(cfg, FlakyTrial,
                                   storage_path=str(tmp_path), mesh=mesh)
    result = runner.run()
    assert result.shutdown
    t = list(result.trials.values())[0]
    assert t.state == "completed"
    assert t.restarts == 1


def test_exhausted_restarts_marks_errored(tmp_path):
    class AlwaysFails(QuadraticTrial):
        def training_data(self):
            raise RuntimeError("always broken")

    cfg = base_config(tmp_path, {
        "name": "single", "metric": "loss", "max_length": {"batches": 4},
    })
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    runner = LocalExperimentRunner(cfg, AlwaysFails,
                                   storage_path=str(tmp_path), mesh=mesh)
    result = runner.run()
    t = list(result.trials.values())[0]
    assert t.state == "errored"
    assert t.restarts == cfg.max_restarts + 1
