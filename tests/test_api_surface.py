"""Round-5 API surface: typed NTSC families, checkpoint mutation, trial
analysis reads, master event log, project depth, experiment metadata/move/
progress, user settings — each new RPC driven against a live master, some
through the GENERATED bindings to prove proto coverage.

≈ the reference's api_{notebook,shell,command,tensorboard}.go,
PatchCheckpoints/DeleteCheckpoints, GetTrialWorkloads, GetMasterLogs,
api_project.go move/archive, PatchUser + user settings
(proto/src/determined/api/v1/api.proto).
"""
import json
import subprocess
import time
import urllib.request
from pathlib import Path

import pytest

from determined_clone_tpu.api import bindings as b
from determined_clone_tpu.api.client import MasterError, MasterSession

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("api-surface")
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/master", timeout=2)
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")
    session = MasterSession("127.0.0.1", port)
    yield {"session": session, "port": port, "tmp": tmp}
    proc.kill()
    proc.wait(timeout=10)


def _seed_trial(session):
    exp = session.post("/api/v1/experiments", {"config": {
        "name": "surface", "entrypoint": "m:T",
        "searcher": {"name": "custom", "metric": "loss"},
        "hyperparameters": {}}})["experiment"]
    session.post(f"/api/v1/experiments/{exp['id']}/searcher/operations",
                 {"ops": [{"type": "create", "request_id": 0, "hparams": {}},
                          {"type": "validate_after", "request_id": 0,
                           "units": 100}]})
    trial = session.get(f"/api/v1/experiments/{exp['id']}")["trials"][0]
    return exp, trial


class TestTypedNtsc:
    def test_notebook_family_via_bindings(self, master):
        session = master["session"]
        resp = b.launch_notebook(
            session, b.V1LaunchNotebookRequest(name="nb-bindings"))
        nb = resp.notebook
        assert nb.task_type == "notebook" and nb.state == "QUEUED"
        listed = b.list_notebooks(session, b.V1ListNotebooksRequest())
        assert any(t.id == nb.id for t in listed.notebooks)
        got = b.get_notebook(session, b.V1GetNotebookRequest(id=nb.id))
        assert got.notebook.name == "nb-bindings"
        killed = b.kill_notebook(session, b.V1KillNotebookRequest(id=nb.id))
        assert killed.notebook.state == "CANCELED"

    def test_shell_and_command_and_tensorboard(self, master):
        session = master["session"]
        sh = session.post("/api/v1/shells", {})["shell"]
        assert sh["task_type"] == "shell"
        cmd = session.post("/api/v1/commands",
                           {"cmd": ["echo", "hi"]})["command"]
        assert cmd["task_type"] == "command"
        tb = session.post("/api/v1/tensorboards",
                          {"experiment_ids": [1, 2]})["tensorboard"]
        assert tb["task_type"] == "tensorboard"
        # a command without argv is rejected (same rule as generic tasks)
        with pytest.raises(MasterError):
            session.post("/api/v1/commands", {})
        for t in (sh, cmd, tb):
            session.post(f"/api/v1/tasks/{t['id']}/kill")

    def test_cross_type_isolation(self, master):
        session = master["session"]
        nb = session.post("/api/v1/notebooks", {})["notebook"]
        # a notebook is not reachable through the shells root
        with pytest.raises(MasterError):
            session.get(f"/api/v1/shells/{nb['id']}")
        # typed lists only carry their own type
        shells = session.get("/api/v1/shells")["shells"]
        assert all(s["task_type"] == "shell" for s in shells)
        session.post(f"/api/v1/notebooks/{nb['id']}/kill")


class TestCheckpointMutation:
    def test_patch_and_bulk_delete(self, master):
        session = master["session"]
        exp, trial = _seed_trial(session)
        tid = trial["id"]
        for i in range(2):
            session.post(f"/api/v1/trials/{tid}/checkpoints",
                         {"uuid": f"ckpt-{exp['id']}-{i}",
                          "metadata": {"steps_completed": i * 10},
                          "resources": {"state.pkl": 100}})
        patched = session.request(
            "PATCH", f"/api/v1/checkpoints/ckpt-{exp['id']}-0",
            {"metadata": {"note": "tagged", "quality": 0.9}})
        assert patched["metadata"]["note"] == "tagged"
        assert patched["metadata"]["steps_completed"] == 0  # merge, not replace

        out = session.post("/api/v1/checkpoints/delete",
                           {"uuids": [f"ckpt-{exp['id']}-0",
                                      f"ckpt-{exp['id']}-1", "nonexistent"]})
        assert out["deleted"] == 2
        with pytest.raises(MasterError):
            session.get(f"/api/v1/checkpoints/ckpt-{exp['id']}-0")


class TestTrialAnalysis:
    def test_workloads_and_profiler_series(self, master):
        session = master["session"]
        _, trial = _seed_trial(session)
        tid = trial["id"]
        for step in (1, 2):
            session.post(f"/api/v1/trials/{tid}/metrics",
                         {"group": "training", "steps_completed": step,
                          "metrics": {"loss": 1.0 / step}})
        session.post(f"/api/v1/trials/{tid}/metrics",
                     {"group": "validation", "steps_completed": 2,
                      "metrics": {"loss": 0.4}})
        w = b.get_trial_workloads(
            session, b.V1GetTrialWorkloadsRequest(id=tid))
        kinds = [x.kind for x in w.workloads]
        assert kinds == ["training", "training", "validation"]
        assert w.workloads[-1].metrics == {"loss": 0.4}

        session.post(f"/api/v1/trials/{tid}/profiler", {"samples": [
            {"time": 1.0, "group": "system", "cpu_util_pct": 55.0,
             "memory_used_gb": 1.5},
            {"time": 1.0, "group": "timing", "batch_s": 0.2},
        ]})
        series = session.get(
            f"/api/v1/trials/{tid}/profiler/series")["series"]
        assert "system/cpu_util_pct" in series
        assert "timing/batch_s" in series
        assert "system/time" not in series


class TestMasterLogs:
    def test_event_log_with_cursor(self, master):
        session = master["session"]
        exp, _ = _seed_trial(session)
        session.post(f"/api/v1/experiments/{exp['id']}/kill")
        deadline = time.time() + 15
        logs = []
        while time.time() < deadline:
            logs = session.get("/api/v1/master/logs?limit=1000")["logs"]
            if any("finished" in l["log"] and
                   f"experiment {exp['id']}" in l["log"] for l in logs):
                break
            time.sleep(0.3)
        assert any(f"experiment {exp['id']} finished" in l["log"]
                   for l in logs), logs[-5:]
        # absolute seq cursor: re-reading from next_offset yields nothing new
        out = session.get("/api/v1/master/logs?limit=1000")
        again = session.get(
            f"/api/v1/master/logs?limit=1000&offset={out['next_offset']}")
        assert again["logs"] == []


class TestProjectDepth:
    def test_crud_move_archive(self, master):
        session = master["session"]
        ws1 = session.post("/api/v1/workspaces", {"name": "pd-ws1"})[
            "workspace"]
        ws2 = session.post("/api/v1/workspaces", {"name": "pd-ws2"})[
            "workspace"]
        proj = session.post(f"/api/v1/workspaces/{ws1['id']}/projects",
                            {"name": "pd-proj"})["project"]
        pid = proj["id"]

        got = session.get(f"/api/v1/projects/{pid}")
        assert got["project"]["name"] == "pd-proj"

        patched = session.request("PATCH", f"/api/v1/projects/{pid}",
                                  {"description": "renovated",
                                   "name": "pd-proj2"})
        assert patched["project"]["description"] == "renovated"
        assert patched["project"]["name"] == "pd-proj2"

        arch = session.post(f"/api/v1/projects/{pid}/archive")
        assert arch["project"]["archived"] is True
        session.post(f"/api/v1/projects/{pid}/unarchive")

        moved = session.post(f"/api/v1/projects/{pid}/move",
                             {"workspace_id": ws2["id"]})
        assert moved["project"]["workspace_id"] == ws2["id"]

        # an experiment moved into the project follows its workspace
        exp, _ = _seed_trial(session)
        m = session.post(f"/api/v1/experiments/{exp['id']}/move",
                         {"project_id": pid})
        assert m["experiment"]["project"] == "pd-proj2"
        assert m["experiment"]["workspace"] == "pd-ws2"

        # a project holding experiments refuses deletion
        with pytest.raises(MasterError):
            session.request("DELETE", f"/api/v1/projects/{pid}")
        session.post(f"/api/v1/experiments/{exp['id']}/kill")
        # move it back out so the project empties, then delete cleanly
        uncategorized = [
            p for p in session.get(
                f"/api/v1/workspaces/{ws1['id']}/projects")["projects"]]
        del uncategorized
        home = session.post(f"/api/v1/workspaces/{ws1['id']}/projects",
                            {"name": "pd-home"})["project"]
        session.post(f"/api/v1/experiments/{exp['id']}/move",
                     {"project_id": home["id"]})
        session.request("DELETE", f"/api/v1/projects/{pid}")
        with pytest.raises(MasterError):
            session.get(f"/api/v1/projects/{pid}")


class TestExperimentMetadata:
    def test_patch_and_progress(self, master):
        session = master["session"]
        exp, trial = _seed_trial(session)
        patched = session.request(
            "PATCH", f"/api/v1/experiments/{exp['id']}",
            {"description": "annotated", "labels": ["tpu", "v5e"]})
        assert patched["experiment"]["description"] == "annotated"
        assert patched["experiment"]["labels"] == ["tpu", "v5e"]

        session.post(f"/api/v1/trials/{trial['id']}/metrics",
                     {"group": "training", "steps_completed": 25,
                      "metrics": {"loss": 0.5}})
        prog = session.get(f"/api/v1/experiments/{exp['id']}/progress")
        assert prog["units_target"] == 100.0
        assert prog["units_done"] == 25.0
        assert prog["progress"] == pytest.approx(0.25)
        session.post(f"/api/v1/experiments/{exp['id']}/kill")


class TestUserSettings:
    def test_settings_bag_and_patch_user(self, master):
        session = master["session"]
        out = session.post("/api/v1/users/settings",
                           {"key": "theme", "value": "dark"})
        assert out["settings"]["theme"] == "dark"
        session.post("/api/v1/users/settings",
                     {"key": "columns", "value": ["id", "state"]})
        got = session.get("/api/v1/users/settings")["settings"]
        assert got == {"theme": "dark", "columns": ["id", "state"]}
        session.request("DELETE", "/api/v1/users/settings")
        assert session.get("/api/v1/users/settings")["settings"] == {}

        users = session.get("/api/v1/users")["users"]
        uid = users[0]["id"]
        patched = session.request("PATCH", f"/api/v1/users/{uid}",
                                  {"display_name": "The Admin"})
        assert patched["user"]["display_name"] == "The Admin"
