"""XLA-level telemetry tests (telemetry/xla.py): explicit compile capture,
fingerprint stability, measured-vs-analytic MFU, and the median/MAD
step-time anomaly detector — plus the Prometheus round-trip of every new
metric family."""
import logging

import jax
import jax.numpy as jnp
import pytest

from determined_clone_tpu.telemetry import (
    MetricsRegistry,
    Tracer,
    parse_prometheus_text,
)
from determined_clone_tpu.telemetry.xla import (
    MfuComparator,
    StepTimeAnomalyDetector,
    aot_compile,
    fingerprint_stablehlo,
)


# ---------------------------------------------------------------------------
# aot_compile: capture, fingerprint, fallback
# ---------------------------------------------------------------------------

class TestAotCompile:
    def test_capture_and_execution_equivalence(self):
        fn = jax.jit(lambda x: (x * 2.0 + 1.0).sum())
        x = jnp.arange(8.0)
        wrapped, record = aot_compile(fn, (x,), program="probe")
        assert record is not None
        assert record.program == "probe"
        assert len(record.fingerprint) == 64  # sha256 hex
        assert record.lower_seconds >= 0.0
        assert record.compile_seconds > 0.0
        # the AOT executable computes the same thing as the jit path
        assert float(wrapped(x)) == float(fn(x))
        # CPU cost model reports per-execution FLOPs (bench relies on it)
        assert record.flops is not None and record.flops > 0
        d = record.as_dict()
        assert d["fingerprint"] == record.fingerprint
        assert None not in d.values()

    def test_fingerprint_stable_across_captures(self):
        """Same program -> same fingerprint (the executable-cache key);
        a different program -> a different one."""
        x = jnp.arange(8.0)
        _, rec_a = aot_compile(jax.jit(lambda v: (v * 2.0).sum()), (x,))
        _, rec_b = aot_compile(jax.jit(lambda v: (v * 2.0).sum()), (x,))
        _, rec_c = aot_compile(jax.jit(lambda v: (v * 3.0).sum()), (x,))
        assert rec_a.fingerprint == rec_b.fingerprint
        assert rec_a.fingerprint != rec_c.fingerprint

    def test_shape_mismatch_falls_back_to_jit(self):
        fn = jax.jit(lambda x: x.sum())
        wrapped, record = aot_compile(fn, (jnp.ones((4,)),))
        assert record is not None
        # a remainder-shaped batch goes through the original jit wrapper
        assert float(wrapped(jnp.ones((3,)))) == 3.0

    def test_non_jitted_callable_degrades_to_noop(self):
        def plain(x):
            return x + 1  # no .lower(): capture must hand it back as-is

        wrapped, record = aot_compile(plain, (1.0,))
        assert wrapped is plain
        assert record is None

    def test_fingerprint_helper_is_sha256(self):
        fp = fingerprint_stablehlo("module @foo {}")
        assert len(fp) == 64
        assert fp == fingerprint_stablehlo("module @foo {}")
        assert fp != fingerprint_stablehlo("module @bar {}")

    def test_export_lands_in_registry_and_tracer(self):
        reg = MetricsRegistry()
        tr = Tracer()
        fn = jax.jit(lambda x: (x @ x).sum())
        wrapped, record = aot_compile(
            fn, (jnp.ones((8, 8)),), program="train_step",
            registry=reg, tracer=tr)
        assert record is not None
        assert reg.counter("xla_compiles_total").value == 1
        spans = [e for e in tr.events() if e["name"] == "xla_compile"]
        assert len(spans) == 1
        assert spans[0]["args"]["program"] == "train_step"
        assert spans[0]["args"]["fingerprint"] == record.fingerprint[:16]


# ---------------------------------------------------------------------------
# Step-time anomaly detector: median/MAD, exactly-once, no self-masking
# ---------------------------------------------------------------------------

class TestAnomalyDetector:
    def test_single_spike_fires_exactly_once(self):
        reg = MetricsRegistry()
        det = StepTimeAnomalyDetector(reg, window=32, threshold=5.0,
                                      min_samples=8)
        flagged = []
        # steady baseline with mild jitter, one 50x straggler at index 20
        for i in range(40):
            dur = 0.5 if i == 20 else 0.010 + 0.0001 * (i % 3)
            flagged.append(det.observe(dur))
        assert flagged.count(True) == 1
        assert flagged[20] is True
        assert det.anomalies == 1
        assert reg.counter("step_time_anomalies_total").value == 1
        ev = det.events[0]
        assert ev["duration_s"] == 0.5
        assert ev["step_index"] == 21  # 1-based position in the stream
        assert ev["limit_s"] < 0.5

    def test_anomaly_not_admitted_so_next_one_still_fires(self):
        """detect-then-admit would raise the baseline after the first
        straggler and mask the second; the window must hold pre-anomaly
        history only."""
        det = StepTimeAnomalyDetector(window=32, threshold=5.0,
                                      min_samples=8)
        for _ in range(16):
            det.observe(0.010)
        assert det.observe(0.5) is True
        assert 0.5 not in det.window
        for _ in range(4):
            det.observe(0.010)
        assert det.observe(0.5) is True
        assert det.anomalies == 2

    def test_warmup_never_flags(self):
        det = StepTimeAnomalyDetector(min_samples=16)
        # compile + cache-warm steps are wildly slow; all inside warmup
        assert not any(det.observe(d) for d in [5.0, 2.0] + [0.01] * 13)

    def test_rel_floor_absorbs_scheduler_jitter(self):
        """An idle-CPU baseline has MAD ~= 0; without the relative floor a
        1.2x scheduler blip would count as 'infinitely many sigmas'."""
        det = StepTimeAnomalyDetector(window=32, threshold=5.0,
                                      min_samples=8, rel_floor=0.05)
        for _ in range(16):
            det.observe(0.010)  # identical durations: MAD == 0
        assert det.observe(0.012) is False  # +20%: jitter, not a straggler
        assert det.observe(0.10) is True    # 10x: a straggler

    def test_instant_event_reaches_tracer(self):
        tr = Tracer()
        det = StepTimeAnomalyDetector(tracer=tr, window=32, min_samples=8)
        for _ in range(10):
            det.observe(0.01)
        det.observe(1.0)
        evs = [e for e in tr.events() if e["name"] == "step_time_anomaly"]
        assert len(evs) == 1 and evs[0]["ph"] == "i"
        assert det.summary()["anomalies"] == 1
        assert det.summary()["recent_events"][0]["duration_s"] == 1.0


# ---------------------------------------------------------------------------
# Measured-vs-analytic MFU comparator
# ---------------------------------------------------------------------------

class TestMfuComparator:
    def test_measured_gauges_and_value(self):
        reg = MetricsRegistry()
        cmp_ = MfuComparator(reg, peak_flops_total=1e9)
        measured = cmp_.report(measured_flops_per_batch=1e6,
                               batches_per_second=100.0,
                               analytic_mfu=0.1)
        assert measured == pytest.approx(0.1)
        assert reg.gauge("measured_flops_per_sec").value == 1e8
        assert reg.gauge("mfu_measured").value == pytest.approx(0.1)
        # within 20% of analytic: no divergence counted
        assert reg.counter("mfu_divergence_total").value == 0

    def test_divergence_counts_and_warn_is_rate_limited(self, caplog):
        reg = MetricsRegistry()
        cmp_ = MfuComparator(reg, peak_flops_total=1e9,
                             warn_period_s=3600.0)
        with caplog.at_level(logging.WARNING,
                             logger="determined_clone_tpu.telemetry.xla"):
            for _ in range(5):  # 2x divergence, five chunks in a row
                cmp_.report(measured_flops_per_batch=2e6,
                            batches_per_second=100.0, analytic_mfu=0.1)
        # every divergent chunk counts; the log line fires once per period
        assert reg.counter("mfu_divergence_total").value == 5
        warns = [r for r in caplog.records if "diverge" in r.message]
        assert len(warns) == 1


# ---------------------------------------------------------------------------
# Prometheus round-trip: every new family survives dump -> parse
# ---------------------------------------------------------------------------

def test_new_families_round_trip_through_prometheus_text():
    reg = MetricsRegistry()
    tr = Tracer()
    aot_compile(jax.jit(lambda x: (x * 2.0).sum()), (jnp.ones((8,)),),
                program="train_step", registry=reg, tracer=tr)
    det = StepTimeAnomalyDetector(reg, window=32, min_samples=8)
    for _ in range(10):
        det.observe(0.01)
    det.observe(1.0)
    MfuComparator(reg, peak_flops_total=1e9).report(
        measured_flops_per_batch=1e6, batches_per_second=10.0)
    reg.counter("flight_records_dropped",
                "flight-recorder records lost to write errors").inc(2)

    parsed = parse_prometheus_text(reg.dump())
    by_name = {}
    for name, labels, value in parsed["samples"]:
        by_name.setdefault(name, []).append((labels, value))
    for family in ("xla_compiles_total", "xla_compile_seconds",
                   "xla_program_flops", "xla_program_bytes_accessed",
                   "step_time_anomalies_total", "measured_flops_per_sec",
                   "mfu_measured", "flight_records_dropped"):
        assert family in by_name, f"{family} missing from exposition"
    assert by_name["step_time_anomalies_total"][0][1] == 1
    assert by_name["flight_records_dropped"][0][1] == 2
    # labeled families carry {program, fingerprint} through the text format
    labels, _ = by_name["xla_compile_seconds"][0]
    assert labels["program"] == "train_step"
    assert len(labels["fingerprint"]) == 16
