"""CLI (`det`) + Python SDK + context-dir upload e2e.

≈ the reference's CLI tests and SDK usage (harness/determined/cli,
common/experimental), plus the context-directory chain: client base64
upload → master storage → agent materialization → trial import
(cli/experiment.py:242 → prep_container.py:29).
"""
import json
import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"

TRIAL_MODULE = '''
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training import JaxTrial
from uploaded_helper import TARGET


class Trial(JaxTrial):
    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.3)

    def loss(self, params, batch, rng):
        return (params["w"] - TARGET) ** 2, {}

    def training_data(self):
        for _ in range(64):
            yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return [np.zeros((2, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 2
'''

HELPER_MODULE = "TARGET = 1.5\n"


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("clisdk")
    workdir = tmp / "agent-work"
    workdir.mkdir()  # deliberately NO model_def here: context upload must work

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "cli-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port,
           "master_addr": f"127.0.0.1:{port}"}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


@pytest.fixture()
def det(cluster, tmp_path, monkeypatch):
    """Invoke the CLI in-process against the fixture master."""
    monkeypatch.setenv("HOME", str(tmp_path))  # isolate ~/.dct auth store
    from determined_clone_tpu.cli import main

    def run(*argv):
        return main(["-m", cluster["master_addr"], *argv])

    return run


def write_model_dir(tmp) -> Path:
    model_dir = tmp / "model_def"
    model_dir.mkdir(exist_ok=True)
    (model_dir / "model_def.py").write_text(TRIAL_MODULE)
    (model_dir / "uploaded_helper.py").write_text(HELPER_MODULE)
    return model_dir


def exp_config(cluster, name="cli-exp", batches=6):
    return {
        "name": name,
        "entrypoint": "model_def:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": batches}},
        "resources": {"slots_per_trial": 1},
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(cluster["tmp"] / "ckpts")},
        "hyperparameters": {},
        "max_restarts": 0,
    }


def test_sdk_experiment_with_context_upload(cluster, tmp_path):
    """The agent workdir has no model code — the trial can only succeed if
    the uploaded context directory (two modules) is materialized."""
    from determined_clone_tpu.sdk import Determined

    d = Determined("127.0.0.1", cluster["port"])
    model_dir = write_model_dir(tmp_path)
    exp = d.create_experiment(exp_config(cluster, "sdk-ctx"),
                              model_dir=str(model_dir))
    state = exp.wait(timeout=180)
    assert state == "COMPLETED"

    trials = exp.trials()
    assert len(trials) == 1
    metrics = trials[0].metrics()
    assert metrics, "no metrics reported"
    # loss on the validation group converges toward (w-1.5)^2 -> 0
    val = [m for m in metrics if m.get("group") == "validation"]
    assert val and val[-1]["metrics"]["loss"] < 0.5

    ckpts = exp.checkpoints()
    assert ckpts
    out = tmp_path / "dl"
    ckpts[-1].download(str(out))
    assert any(out.iterdir())

    top = exp.top_checkpoint()
    assert top is not None

    # lifecycle surface: archive the finished experiment, then delete it
    exp.archive()
    assert exp.describe()["experiment"]["archived"] is True
    exp.archive(archived=False)
    exp.delete()
    import pytest as _pytest

    from determined_clone_tpu.api.client import MasterError

    with _pytest.raises(MasterError):
        exp.describe()


def test_cli_full_surface(cluster, det, tmp_path, capsys):
    import yaml

    # master info
    assert det("master", "info") == 0
    info = json.loads(capsys.readouterr().out)
    assert info["cluster_name"] == "dct"

    # experiment create from YAML + follow
    model_dir = write_model_dir(tmp_path)
    cfg_path = tmp_path / "config.yaml"
    cfg_path.write_text(yaml.safe_dump(exp_config(cluster, "cli-exp")))
    rc = det("experiment", "create", str(cfg_path), str(model_dir),
             "--follow", "--timeout", "180")
    out = capsys.readouterr().out
    assert rc == 0
    assert "COMPLETED" in out
    exp_id = int(out.split("Created experiment ")[1].split()[0])

    # listing/describe/metrics/logs
    assert det("experiment", "list") == 0
    assert f"cli-exp" in capsys.readouterr().out
    assert det("experiment", "describe", str(exp_id)) == 0
    detail = json.loads(capsys.readouterr().out)
    trial_id = detail["trials"][0]["id"]
    assert det("trial", "metrics", str(trial_id)) == 0
    assert json.loads(capsys.readouterr().out)
    assert det("trial", "logs", str(trial_id)) == 0
    capsys.readouterr()

    # checkpoints: list + download
    assert det("checkpoint", "list", str(exp_id)) == 0
    uuid = capsys.readouterr().out.splitlines()[2].split("|")[0].strip()
    dl_dir = tmp_path / "ckpt-dl"
    assert det("checkpoint", "download", uuid, "-o", str(dl_dir)) == 0
    capsys.readouterr()
    assert any(dl_dir.iterdir())

    # model registry round trip via CLI
    assert det("model", "create", "cli-model") == 0
    capsys.readouterr()
    assert det("model", "register-version", "cli-model", uuid) == 0
    assert "version 1" in capsys.readouterr().out

    # agents, job queue, workspaces
    assert det("agent", "list") == 0
    assert "cli-agent" in capsys.readouterr().out
    assert det("job", "list") == 0
    capsys.readouterr()
    assert det("workspace", "create", "cli-ws") == 0
    capsys.readouterr()
    assert det("workspace", "list") == 0
    assert "cli-ws" in capsys.readouterr().out

    # templates
    tpl_path = tmp_path / "tpl.yaml"
    tpl_path.write_text(yaml.safe_dump({"max_restarts": 2}))
    assert det("template", "set", "cli-tpl", str(tpl_path)) == 0
    capsys.readouterr()
    assert det("template", "list") == 0
    assert "cli-tpl" in capsys.readouterr().out

    # config override plumbing
    cfg2 = exp_config(cluster, "cli-exp2", batches=2)
    cfg2_path = tmp_path / "config2.yaml"
    cfg2_path.write_text(yaml.safe_dump(cfg2))
    assert det("experiment", "create", str(cfg2_path), str(model_dir),
               "--config-override", "name=overridden") == 0
    capsys.readouterr()
    assert det("experiment", "list") == 0
    assert "overridden" in capsys.readouterr().out


def test_cli_auth_login_logout(cluster, det, capsys):
    assert det("user", "login", "admin", "--password", "") == 0
    capsys.readouterr()
    assert det("user", "whoami") == 0
    assert "admin" in capsys.readouterr().out
    assert det("user", "create", "cliuser", "--password", "pw") == 0
    capsys.readouterr()
    assert det("user", "list") == 0
    assert "cliuser" in capsys.readouterr().out
    assert det("user", "logout") == 0
    capsys.readouterr()


def test_cli_shell_lifecycle(cluster, det, capsys):
    assert det("shell", "start", "--name", "cli-sh") == 0
    out = capsys.readouterr().out
    task_id = out.split("Started shell ")[1].strip()

    session = cluster["session"]
    deadline = time.time() + 60
    while time.time() < deadline:
        t = session.get_task(task_id)
        if t["state"] == "RUNNING" and t["proxy_address"]:
            break
        time.sleep(0.3)
    else:
        pytest.fail("shell task never came up")

    rc = det("shell", "exec", task_id, "echo", "from-cli")
    out = capsys.readouterr().out
    assert rc == 0
    assert "from-cli" in out

    assert det("task", "list") == 0
    assert task_id in capsys.readouterr().out
    assert det("task", "kill", task_id) == 0
    capsys.readouterr()
