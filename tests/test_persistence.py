"""Persistence depth (VERDICT r4 #4): relational metrics + materialized
summary, schema migrations with backfill, log retention, and the
follow-thread budget.

≈ the reference's master/internal/db/postgres_trial.go (typed metric
tables), master/static/srv/calculate-full-trial-summary-metrics.sql
(summary materialization — here incremental upserts), and
master/static/migrations (forward migration ladder — here PRAGMA
user_version stamps in store.cc).
"""
import json
import sqlite3
import threading
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"


def _start_master(data_dir, *extra_args):
    import socket
    import subprocess

    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir", str(data_dir),
         "--db", "sqlite", *extra_args],
        stdout=__import__("subprocess").PIPE,
        stderr=__import__("subprocess").STDOUT)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/master", timeout=2)
            return proc, port
        except Exception:
            time.sleep(0.2)
    proc.kill()
    pytest.fail("master did not come up")


def _req(port, method, path, body=None):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return json.loads(resp.read() or "{}")


def _seed_trial(port):
    """Experiment + one custom-searcher trial the master will accept
    metric reports for (no agents needed)."""
    exp = _req(port, "POST", "/api/v1/experiments", {"config": {
        "name": "persist", "entrypoint": "m:T",
        "searcher": {"name": "custom", "metric": "loss"},
        "hyperparameters": {}}})["experiment"]
    _req(port, "POST",
         f"/api/v1/experiments/{exp['id']}/searcher/operations",
         {"ops": [{"type": "create", "request_id": 0, "hparams": {}},
                  {"type": "validate_after", "request_id": 0,
                   "units": 100}]})
    trial = _req(port, "GET", f"/api/v1/experiments/{exp['id']}")["trials"][0]
    return exp["id"], trial["id"]


def test_metric_summary_materialized(tmp_path):
    proc, port = _start_master(tmp_path / "data")
    try:
        info = _req(port, "GET", "/api/v1/master")
        assert info["store"] == {"kind": "sqlite", "schema_version": 2}
        _, tid = _seed_trial(port)
        for step in range(1, 21):
            _req(port, "POST", f"/api/v1/trials/{tid}/metrics",
                 {"group": "training", "steps_completed": step,
                  "metrics": {"loss": 1.0 / step, "acc": step / 20.0,
                              "note": "non-numeric-ignored"}})
        _req(port, "POST", f"/api/v1/trials/{tid}/metrics",
             {"group": "validation", "steps_completed": 20,
              "metrics": {"loss": 0.07}})

        rows = _req(port, "GET",
                    f"/api/v1/trials/{tid}/metrics?limit=100")["metrics"]
        assert len(rows) == 21
        # offset paging on the typed table
        page = _req(port, "GET",
                    f"/api/v1/trials/{tid}/metrics?limit=5&offset=18")[
                        "metrics"]
        assert len(page) == 3

        summary = _req(port, "GET",
                       f"/api/v1/trials/{tid}/metrics/summary")["summary"]
        by_key = {(s["group"], s["name"]): s for s in summary}
        loss = by_key[("training", "loss")]
        assert loss["count"] == 20
        assert loss["min"] == pytest.approx(1.0 / 20)
        assert loss["max"] == pytest.approx(1.0)
        assert loss["last"] == pytest.approx(1.0 / 20)
        assert loss["last_step"] == 20
        assert loss["mean"] == pytest.approx(
            sum(1.0 / s for s in range(1, 21)) / 20)
        assert by_key[("validation", "loss")]["count"] == 1
        # the non-numeric metric never aggregates
        assert ("training", "note") not in by_key
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_migration_v2_backfills_legacy_metrics(tmp_path):
    data = tmp_path / "data"
    proc, port = _start_master(data)
    try:
        _, tid = _seed_trial(port)
    finally:
        # graceful stop: SIGTERM saves the snapshot and closes sqlite
        # cleanly (kill() would race the 0.5 s persistence tick)
        proc.terminate()
        proc.wait(timeout=10)

    # simulate a pre-v2 database: metric history in the generic records
    # stream, no typed tables, version stamp rolled back
    db = sqlite3.connect(data / "master.db")
    db.execute("DROP TABLE metrics")
    db.execute("DROP TABLE metric_summary")
    stream = f"trial-{tid}-metrics.jsonl"
    for step in range(1, 11):
        db.execute(
            "INSERT INTO records (stream, seq, body) VALUES (?, ?, ?)",
            (stream, step, json.dumps({
                "group": "training", "steps_completed": step,
                "metrics": {"loss": float(step)}})))
    db.execute("PRAGMA user_version = 1")
    db.commit()
    db.close()

    proc, port = _start_master(data)
    try:
        # the v2 migration re-created the tables and backfilled history
        rows = _req(port, "GET",
                    f"/api/v1/trials/{tid}/metrics?limit=100")["metrics"]
        assert len(rows) == 10
        summary = _req(port, "GET",
                       f"/api/v1/trials/{tid}/metrics/summary")["summary"]
        [loss] = [s for s in summary
                  if (s["group"], s["name"]) == ("training", "loss")]
        assert loss["count"] == 10
        assert loss["min"] == 1.0 and loss["max"] == 10.0
    finally:
        proc.kill()
        proc.wait(timeout=10)
    # and the stamp moved forward
    db = sqlite3.connect(data / "master.db")
    assert db.execute("PRAGMA user_version").fetchone()[0] == 2
    db.close()


def test_files_to_sqlite_switch_keeps_metric_history(tmp_path):
    """Backend switch: metric history reported under --db files must stay
    visible through the typed tables after reopening with --db sqlite
    (legacy .jsonl import must run BEFORE the v2 backfill reads records)."""
    data = tmp_path / "data"
    proc, port = _start_master(data, "--db", "files")
    try:
        _, tid = _seed_trial(port)
        for step in range(1, 6):
            _req(port, "POST", f"/api/v1/trials/{tid}/metrics",
                 {"group": "training", "steps_completed": step,
                  "metrics": {"loss": float(step)}})
    finally:
        proc.terminate()
        proc.wait(timeout=10)

    proc, port = _start_master(data)  # sqlite
    try:
        assert _req(port, "GET", "/api/v1/master")["store"]["kind"] == \
            "sqlite"
        rows = _req(port, "GET",
                    f"/api/v1/trials/{tid}/metrics?limit=100")["metrics"]
        assert len(rows) == 5
        summary = _req(port, "GET",
                       f"/api/v1/trials/{tid}/metrics/summary")["summary"]
        [loss] = [s for s in summary
                  if (s["group"], s["name"]) == ("training", "loss")]
        assert loss["count"] == 5 and loss["max"] == 5.0
    finally:
        proc.kill()
        proc.wait(timeout=10)


def test_log_retention_trims_finished_tasks(tmp_path):
    proc, port = _start_master(
        tmp_path / "data", "--config", str(_retention_config(tmp_path)))
    try:
        exp_id, tid = _seed_trial(port)
        alloc = f"trial-{tid}.0"
        for i in range(0, 500, 100):
            _req(port, "POST", f"/api/v1/allocations/{alloc}/logs",
                 {"logs": [f"line-{i + j}" for j in range(100)]})
        logs = _req(port, "GET",
                    f"/api/v1/allocations/{alloc}/logs?limit=1000")["logs"]
        assert len(logs) == 500  # running: nothing trimmed

        _req(port, "POST", f"/api/v1/experiments/{exp_id}/kill")
        deadline = time.time() + 15
        while time.time() < deadline:
            logs = _req(port, "GET",
                        f"/api/v1/allocations/{alloc}/logs?limit=1000")[
                            "logs"]
            if len(logs) <= 50:
                break
            time.sleep(0.5)
        assert len(logs) == 50
        # the newest tail survived, not the head
        assert "line-499" in json.dumps(logs[-1])
    finally:
        proc.kill()
        proc.wait(timeout=10)


def _retention_config(tmp_path):
    cfg = tmp_path / "master.yaml"
    cfg.write_text("log_retention_records: 50\n"
                   "log_retention_interval: 1\n"
                   "log_retention_grace: 1\n")
    return cfg


def test_follower_thread_budget(tmp_path):
    cfg = tmp_path / "master.yaml"
    cfg.write_text("max_log_followers: 2\n")
    proc, port = _start_master(tmp_path / "data", "--config", str(cfg))
    try:
        _, tid = _seed_trial(port)
        alloc = f"trial-{tid}.0"
        _req(port, "POST", f"/api/v1/allocations/{alloc}/logs",
             {"logs": ["hello"]})

        elapsed = []
        lock = threading.Lock()

        def follow():
            t0 = time.perf_counter()
            _req(port, "GET",
                 f"/api/v1/allocations/{alloc}/logs"
                 f"?follow=5&offset=1&limit=10")
            with lock:
                elapsed.append(time.perf_counter() - t0)

        threads = [threading.Thread(target=follow) for _ in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fast = [e for e in elapsed if e < 2.0]
        held = [e for e in elapsed if e >= 2.0]
        # 2 slots hold the full 5 s window; the 3 over-budget followers
        # degrade to immediate responses instead of pinning threads
        assert len(held) == 2, elapsed
        assert len(fast) == 3, elapsed
    finally:
        proc.kill()
        proc.wait(timeout=10)
