"""Multi-agent gang e2e: 2 agents, slots_per_trial=2 — the master gangs
both, the two trial processes rendezvous, bring up jax.distributed (CPU
backend), and train data-parallel over the 2-process world.

≈ the reference's distributed e2e (devcluster double.devcluster.yaml per
managed_cluster.py:16 + nightly test_distributed.py): multi-node without
real hardware via multiple agent processes on one host.
"""
import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"

TRIAL_MODULE = '''
import jax
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training import JaxTrial


class Trial(JaxTrial):
    def initial_params(self, rng):
        # prove the world really is 2 processes
        assert jax.process_count() == 2, jax.process_count()
        assert jax.device_count() >= 2
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.2)

    def loss(self, params, batch, rng):
        return (params["w"] - 2.0) ** 2, {}

    def training_data(self):
        for _ in range(64):
            yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return [np.zeros((2, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 2
'''


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("gang")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    base_env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    # each agent contributes 1 slot; the XLA flag is NOT forced to 8 here so
    # each process owns its own single CPU "chip" (a 2-host world)
    base_env["XLA_FLAGS"] = ""
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=base_env,
    )
    agents = []
    for i in range(2):
        workdir = tmp / f"agent-{i}"
        workdir.mkdir()
        (workdir / "model_def.py").write_text(TRIAL_MODULE)
        env = {**base_env, "DCT_AGENT_SLOTS": "1"}
        agents.append(subprocess.Popen(
            [str(AGENT_BIN), "--master-port", str(port),
             "--id", f"gang-agent-{i}", "--work-dir", str(workdir)],
            cwd=str(workdir),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        ))

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if len(session.list_agents()) == 2:
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        for a in agents:
            a.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port}

    for a in agents:
        a.kill()
    master.kill()
    for a in agents:
        a.wait(timeout=10)
    master.wait(timeout=10)


def wait_for(predicate, timeout=240, interval=1.0, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def test_two_agent_gang_trains(cluster):
    session = cluster["session"]
    exp = session.create_experiment({
        "name": "gang2",
        "entrypoint": "model_def:Trial",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 4}},
        "resources": {"slots_per_trial": 2},
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(cluster["tmp"] / "ckpts")},
        "hyperparameters": {},
        "max_restarts": 0,
    })

    def done():
        d = session.get_experiment(exp["id"])
        state = d["experiment"]["state"]
        if state == "ERRORED":
            trial = d["trials"][0]
            logs = session.task_logs(
                f"trial-{trial['id']}.0", limit=200)
            raise AssertionError(
                "gang experiment ERRORED:\n" +
                "\n".join(l.get("log", "") for l in logs[-40:]))
        return d if state == "COMPLETED" else None

    detail = wait_for(done, desc="gang completion")
    trial = detail["trials"][0]
    assert trial["state"] == "COMPLETED"

    # both ranks joined one allocation (world_size 2) and rendezvoused
    queue_done = session.get(
        f"/api/v1/allocations/trial-{trial['id']}.0/rendezvous")
    assert queue_done["world_size"] == 2
    assert len(queue_done["members"]) == 2

    # validation metrics flowed from the chief
    metrics = session.trial_metrics(trial["id"])
    val = [m for m in metrics if m.get("group") == "validation"]
    assert val and val[-1]["metrics"]["loss"] < 0.5

    # the gang admission shows up in the scheduler's control-plane
    # telemetry: a 2-reservation fit counts as one admitted gang, and the
    # full lifecycle ran (submitted → scheduled → running → completed)
    sched = session.get("/api/v1/cluster/scheduler")
    c = sched["counters"]
    assert c["gangs_admitted"] >= 1
    assert c["submitted"] >= 1 and c["scheduled"] >= 1
    assert c["running"] >= 1 and c["completed"] >= 1
    assert "gang_wait_ticks" in c  # ticks spent waiting are tracked too
    lat = sched["latency"]["submit_to_running_seconds"]
    assert lat["count"] >= 1 and lat["p50"] > 0

    # and in the Prometheus exposition, including the per-pool gauge family
    import urllib.request

    text = urllib.request.urlopen(
        f"http://127.0.0.1:{cluster['port']}/metrics", timeout=10
    ).read().decode()
    assert "dct_master_sched_gangs_admitted_total" in text
    assert "dct_master_sched_gang_waiting" in text
