"""Per-device memory telemetry tests (telemetry/device.py): the all-devices
snapshot that replaced the profiler's device-0-only sample, the CPU/RSS
fallback, and the process-wide peak watermark the trainer publishes as
``device_memory_peak_bytes``."""
import jax

from determined_clone_tpu.telemetry import MetricsRegistry
from determined_clone_tpu.telemetry.device import (
    DeviceMemoryMonitor,
    device_memory_snapshot,
    device_memory_stats,
    host_rss_bytes,
    take_peak_bytes,
)


class TestSnapshot:
    def test_cpu_fallback_attributes_rss_once(self):
        """On the virtual 8-device CPU mesh every device shares one address
        space: the RSS stand-in must appear exactly once (labeled
        ``device="host"``), while each virtual device gets its OWN
        live-buffers record — previously all 8 collapsed into one RSS sum
        and per-device skew was invisible."""
        records = device_memory_snapshot()
        assert records, "snapshot empty on a live backend"
        rss_records = [r for r in records if r["source"] == "rss"]
        if any(r["source"] == "memory_stats" for r in records):
            # a real accelerator backend: per-device stats, all devices
            assert len(records) == len(jax.local_devices())
        else:
            assert len(rss_records) == 1
            rec = rss_records[0]
            assert rec["bytes_in_use"] > 0
            assert rec["peak_bytes_in_use"] >= rec["bytes_in_use"]
            assert rec["device"] == "host"
            live = [r for r in records if r["source"] == "live_buffers"]
            assert len(live) == len(jax.local_devices())
            assert {r["device"] for r in live} == {
                f"{d.platform}:{d.id}" for d in jax.local_devices()}

    def test_flat_stats_keep_historical_keys(self):
        stats = device_memory_stats()
        # the PR-2 sample keys the profiler has always shipped, now summed
        # across every local device instead of read off device 0
        assert stats["device_bytes_in_use"] > 0
        assert "device_bytes_limit" in stats
        assert stats["device_count"] >= 1

    def test_profiler_delegates_to_device_module(self):
        from determined_clone_tpu.profiler import _device_memory_stats

        stats = _device_memory_stats()
        assert stats["device_bytes_in_use"] > 0
        assert stats["device_count"] >= 1

    def test_host_rss_readable_on_linux(self):
        rss = host_rss_bytes()
        assert rss is None or rss > 1 << 20  # a python process is >1 MiB


class TestWatermark:
    def test_snapshot_raises_watermark_and_take_resets(self):
        take_peak_bytes()  # drain whatever earlier tests left behind
        records = device_memory_snapshot()
        # live_buffers bytes already live inside the host rss record (one
        # address space), so the watermark intentionally skips them
        total = sum(r["bytes_in_use"] for r in records
                    if r["source"] != "live_buffers")
        assert take_peak_bytes() >= total > 0
        # reset: nothing sampled since the take
        assert take_peak_bytes() == 0.0

    def test_monitor_take_peak_covers_other_samplers(self):
        """The profiler's 1 Hz thread samples through the module-level
        function, not the trainer's monitor instance; the monitor's take
        must still see that high-water mark."""
        mon = DeviceMemoryMonitor()
        mon.take_peak()
        device_memory_stats()  # an "other actor" sample (profiler thread)
        assert mon.take_peak() > 0


class TestMonitorGauges:
    def test_sample_feeds_labeled_gauges(self):
        reg = MetricsRegistry()
        mon = DeviceMemoryMonitor(reg)
        stats = mon.sample()
        assert stats["device_bytes_in_use"] > 0
        text = reg.dump()
        assert "device_memory_bytes_in_use{" in text
        assert 'source="' in text
        assert mon.take_peak() >= stats["device_bytes_in_use"]

    def test_registry_free_monitor_still_tracks_peak(self):
        mon = DeviceMemoryMonitor()
        mon.sample()
        assert mon.take_peak() > 0
        # after the take, no sample -> instance peak is back to zero;
        # only the shared watermark (raised by other actors) can lift it
        device_memory_snapshot()
        assert mon.take_peak() > 0
