"""Pallas flash attention: numerics vs the XLA reference, gradients,
shape guards, and GPT integration (interpret mode on CPU — same kernel
code path the TPU compiles)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from determined_clone_tpu.ops.attention import mha
from determined_clone_tpu.ops.flash_attention import flash_attention


def _qkv(B=2, T=128, H=2, D=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_matches_mha(causal):
    q, k, v = _qkv()
    ref = mha(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    assert jnp.max(jnp.abs(ref - out)) < 1e-4


def test_uneven_q_k_blocks():
    # q blocks smaller than k blocks and vice versa
    q, k, v = _qkv(T=128)
    ref = mha(q, k, v, causal=True)
    for bq, bk in [(32, 64), (64, 32), (128, 128)]:
        out = flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk)
        assert jnp.max(jnp.abs(ref - out)) < 1e-4, (bq, bk)


def test_block_clamps_to_seq():
    # seq shorter than the default blocks: clamp instead of error
    q, k, v = _qkv(T=64)
    out = flash_attention(q, k, v)  # default block 128 > 64
    assert jnp.max(jnp.abs(mha(q, k, v) - out)) < 1e-4


def test_indivisible_seq_rejected():
    q, k, v = _qkv(T=96)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=64, block_k=64)


def test_gradients_match_reference():
    q, k, v = _qkv(T=128)

    def f_flash(q, k, v):
        return (flash_attention(q, k, v, block_q=64, block_k=64) ** 2).sum()

    def f_ref(q, k, v):
        return (mha(q, k, v) ** 2).sum()

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert jnp.max(jnp.abs(a - b)) < 1e-3


def test_bf16_inputs():
    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(T=128))
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    assert out.dtype == jnp.bfloat16
    ref = mha(q, k, v)
    assert jnp.max(jnp.abs(ref.astype(jnp.float32) -
                           out.astype(jnp.float32))) < 0.05


def test_gpt_with_flash_attention_trains():
    import optax

    from determined_clone_tpu.models import gpt
    from determined_clone_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, d_model=64, n_heads=4,
                        d_ff=128, max_seq_len=64, remat=False,
                        attention_impl="flash", attention_block_size=32)
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 33), 0, 128)

    # flash output agrees with mha inside the full model (BEFORE training:
    # the train step donates the state, freeing these param buffers)
    # explicit mha: on TPU the config default ("auto") resolves to flash,
    # which would make this parity check compare the kernel to itself
    cfg_ref = gpt.GPTConfig(vocab_size=128, n_layers=2, d_model=64, n_heads=4,
                            d_ff=128, max_seq_len=64, remat=False,
                            attention_impl="mha")
    logits_ref = gpt.apply(params, cfg_ref, tokens[:, :-1])
    logits_flash = gpt.apply(params, cfg, tokens[:, :-1])
    assert jnp.max(jnp.abs(logits_ref - logits_flash)) < 0.05

    tx = optax.sgd(0.1)
    state = create_train_state(params, tx, jax.random.PRNGKey(1))

    def loss_fn(p, b, rng):
        return gpt.loss_fn(p, cfg, b[:, :-1], b[:, 1:]), {}

    step = make_train_step(loss_fn, tx)
    state, m1 = step(state, tokens)
    state, m2 = step(state, tokens)
    assert jnp.isfinite(m2["loss"])
    assert float(m2["loss"]) < float(m1["loss"])


def test_auto_attention_resolves_per_backend():
    """TPU-first default: "auto" must pick the fused kernel on TPU and
    plain XLA attention elsewhere, and unknown impls fail loudly."""
    import dataclasses

    from determined_clone_tpu.models import gpt

    cfg = gpt.GPTConfig.tiny()
    assert cfg.attention_impl == "auto"  # the out-of-the-box default
    # literal per-backend expectations (NOT the implementation's own
    # predicate, which would make this assertion tautological)
    if jax.default_backend() == "tpu":
        assert gpt.resolved_attention_impl(cfg) == "flash"
    else:
        assert gpt.resolved_attention_impl(cfg) == "mha"
    assert gpt.resolved_attention_impl(
        dataclasses.replace(cfg, attention_impl="flash")) == "flash"
    with pytest.raises(ValueError, match="bogus"):
        gpt.resolved_attention_impl(
            dataclasses.replace(cfg, attention_impl="bogus"))


def test_flash_mha_loss_parity_over_training():
    """Kernel regression gate (VERDICT r3 #2): same-seed training with the
    Pallas kernel must track the XLA-attention loss curve step for step.
    A numerics bug that still 'trains' would slip a smoke test; a
    per-step curve comparison catches it."""
    import dataclasses

    import optax

    from determined_clone_tpu.models import gpt
    from determined_clone_tpu.training.train_step import (
        create_train_state,
        make_train_step,
    )

    cfg_flash = gpt.GPTConfig(
        vocab_size=128, n_layers=2, d_model=64, n_heads=4, d_ff=128,
        max_seq_len=64, remat=False, attention_impl="flash",
        attention_block_size=32)
    cfg_mha = dataclasses.replace(cfg_flash, attention_impl="mha")
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 65), 0, 128)

    curves = {}
    for name, cfg in [("flash", cfg_flash), ("mha", cfg_mha)]:
        params = gpt.init(jax.random.PRNGKey(0), cfg)
        tx = optax.adam(3e-3)
        state = create_train_state(params, tx, jax.random.PRNGKey(1))

        def loss_fn(p, b, rng, cfg=cfg):
            return gpt.loss_fn(p, cfg, b[:, :-1], b[:, 1:]), {}

        step = make_train_step(loss_fn, tx)
        losses = []
        for _ in range(6):
            state, m = step(state, tokens)
            losses.append(float(m["loss"]))
        curves[name] = losses

    for lf, lm in zip(curves["flash"], curves["mha"]):
        assert abs(lf - lm) / max(abs(lm), 1e-6) < 0.02, (curves)
    # and both actually trained
    assert curves["flash"][-1] < curves["flash"][0]


def test_flash_pads_indivisible_seq_in_gpt():
    """The everyday loss pattern slices tokens[:, :-1], giving T values
    (e.g. 2047) not divisible by the kernel block. The model must pad and
    slice transparently and still match mha numerics."""
    import dataclasses

    from determined_clone_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=128, n_layers=2, d_model=64, n_heads=4,
                        d_ff=128, max_seq_len=64, remat=False,
                        attention_impl="flash", attention_block_size=32)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 50), 0, 128)
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    logits_flash = gpt.apply(params, cfg, tokens)  # T=50, blk=32 -> pad 14
    logits_mha = gpt.apply(
        params, dataclasses.replace(cfg, attention_impl="mha"), tokens)
    assert logits_flash.shape == logits_mha.shape
    assert jnp.max(jnp.abs(logits_flash - logits_mha)) < 0.05
