"""Sustained soak gate (VERDICT r4 #4): the BASELINE.md k6-equivalent.

≈ the reference's nightly k6 run (performance/src/api_performance_tests.ts:
336-374 — 25 ramping VUs, 20 min, ~40 endpoint groups, p95 < 1 s). Scaled
to CI wall-clock: DCT_SOAK_SECONDS (default 120) of sustained load from
25 VUs across every GET endpoint group, WHILE 12 log followers long-poll a
live stream being appended to and a WebSocket relay shuttles frames
through the reverse proxy. The same p95 < 1 s / <5% failure gates apply
throughout — not just at the end.
"""
import base64
import hashlib
import json
import os
import socket
import statistics
import struct
import subprocess
import threading
import time
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"

SOAK_SECONDS = float(os.environ.get("DCT_SOAK_SECONDS", "120"))
VUS = 25
FOLLOWERS = 12
P95_BUDGET_S = 1.0
WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("soak")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "data"), "--db", "sqlite"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/master", timeout=2)
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")
    yield {"port": port, "tmp": tmp}
    proc.kill()
    proc.wait(timeout=10)


def _req(port, method, path, body=None, timeout=30):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        return json.loads(resp.read() or "{}")


def _seed(port):
    """History across every entity family the GET groups page over."""
    ws = _req(port, "POST", "/api/v1/workspaces",
              {"name": "soak-ws"})["workspace"]
    _req(port, "POST", f"/api/v1/workspaces/{ws['id']}/projects",
         {"name": "soak-proj"})
    _req(port, "POST", "/api/v1/models",
         {"name": "soak-model", "description": "soak"})
    _req(port, "POST", "/api/v1/webhooks",
         {"url": "http://127.0.0.1:9/hook", "triggers": []})
    _req(port, "POST", "/api/v1/templates",
         {"name": "soak-tpl", "config": {"resources": {"slots_per_trial": 1}}})
    exp = _req(port, "POST", "/api/v1/experiments", {"config": {
        "name": "soak", "entrypoint": "m:T",
        "searcher": {"name": "custom", "metric": "loss"},
        "hyperparameters": {"lr": 0.1}}})["experiment"]
    _req(port, "POST",
         f"/api/v1/experiments/{exp['id']}/searcher/operations",
         {"ops": [{"type": "create", "request_id": 0,
                   "hparams": {"lr": 0.1}},
                  {"type": "create", "request_id": 1,
                   "hparams": {"lr": 0.2}},
                  {"type": "validate_after", "request_id": 0,
                   "units": 10_000},
                  {"type": "validate_after", "request_id": 1,
                   "units": 10_000}]})
    trials = _req(port, "GET", f"/api/v1/experiments/{exp['id']}")["trials"]
    for t in trials:
        for step in range(0, 1500, 50):
            _req(port, "POST", f"/api/v1/trials/{t['id']}/metrics",
                 {"group": "training", "steps_completed": step,
                  "metrics": {"loss": 1.0 / (step + 1),
                              "acc": step / 1500.0}})
    alloc = f"trial-{trials[0]['id']}.0"
    for i in range(0, 1000, 100):
        _req(port, "POST", f"/api/v1/allocations/{alloc}/logs",
             {"logs": [f"seed-{i + j}" for j in range(100)]})
    return exp["id"], [t["id"] for t in trials], alloc


class WsEchoServer:
    """Accepts upgrades and echoes text frames (one connection at a time)."""

    def __init__(self):
        self.sock = socket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(4)
        self.port = self.sock.getsockname()[1]
        self.running = True
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        while self.running:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            try:
                head = b""
                while b"\r\n\r\n" not in head:
                    chunk = conn.recv(4096)
                    if not chunk:
                        raise ConnectionError
                    head += chunk
                key = next(
                    line.split(b":", 1)[1].strip()
                    for line in head.split(b"\r\n")
                    if line.lower().startswith(b"sec-websocket-key"))
                accept = base64.b64encode(hashlib.sha1(
                    key + WS_GUID.encode()).digest()).decode()
                conn.sendall(
                    ("HTTP/1.1 101 Switching Protocols\r\n"
                     "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                     f"Sec-WebSocket-Accept: {accept}\r\n\r\n").encode())
                while self.running:
                    payload = _ws_decode(conn)
                    conn.sendall(_ws_encode(b"echo:" + payload))
            except Exception:
                pass
            finally:
                conn.close()

    def close(self):
        self.running = False
        self.sock.close()


def _ws_encode(payload, mask=False):
    head = bytes([0x81])
    n = len(payload)
    mbit = 0x80 if mask else 0
    if n < 126:
        head += bytes([mbit | n])
    else:
        head += bytes([mbit | 126]) + struct.pack(">H", n)
    if mask:
        key = os.urandom(4)
        return head + key + bytes(b ^ key[i % 4]
                                  for i, b in enumerate(payload))
    return head + payload


def _recv_exact(sock, n):
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        data += chunk
    return data


def _ws_decode(sock):
    b0, b1 = _recv_exact(sock, 2)
    masked = b1 & 0x80
    n = b1 & 0x7F
    if n == 126:
        n = struct.unpack(">H", _recv_exact(sock, 2))[0]
    elif n == 127:
        n = struct.unpack(">Q", _recv_exact(sock, 8))[0]
    key = _recv_exact(sock, 4) if masked else None
    payload = _recv_exact(sock, n)
    if key:
        payload = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
    return payload


def test_sustained_soak_p95_with_followers_and_ws(master):
    port = master["port"]
    exp_id, trial_ids, alloc = _seed(port)

    paths = [
        "/api/v1/experiments",
        f"/api/v1/experiments/{exp_id}",
        f"/api/v1/experiments/{exp_id}/checkpoints",
        f"/api/v1/trials/{trial_ids[0]}",
        f"/api/v1/trials/{trial_ids[0]}/metrics?limit=500",
        f"/api/v1/trials/{trial_ids[-1]}/metrics?limit=100&offset=20",
        f"/api/v1/trials/{trial_ids[0]}/metrics/summary",
        f"/api/v1/allocations/{alloc}/logs?limit=300",
        f"/api/v1/allocations/{alloc}/logs?limit=50&offset=900",
        "/api/v1/agents",
        "/api/v1/job-queue",
        "/api/v1/master",
        "/api/v1/master/config",
        "/api/v1/workspaces",
        "/api/v1/models",
        "/api/v1/webhooks",
        "/api/v1/templates",
        "/api/v1/users",
        "/metrics",
    ]

    stop = threading.Event()
    lock = threading.Lock()
    window_latencies = []   # (t_end, latency) for per-window p95
    errors = []
    follower_rounds = [0]
    ws_rounds = [0]

    def vu(idx):
        i = 0
        while not stop.is_set():
            path = paths[(idx + i) % len(paths)]
            i += 1
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=30) as r:
                    r.read()
                with lock:
                    window_latencies.append(
                        (time.monotonic(), time.perf_counter() - t0))
            except Exception as exc:  # noqa: BLE001
                with lock:
                    errors.append(f"{path}: {exc!r}")

    def follower(idx):
        offset = 0
        while not stop.is_set():
            try:
                out = _req(port, "GET",
                           f"/api/v1/allocations/{alloc}/logs"
                           f"?follow=3&offset={offset}&limit=200",
                           timeout=30)
                offset = out.get("next_offset", offset)
                with lock:
                    follower_rounds[0] += 1
            except Exception as exc:  # noqa: BLE001
                with lock:
                    errors.append(f"follower: {exc!r}")

    def log_writer():
        i = 0
        while not stop.is_set():
            try:
                _req(port, "POST", f"/api/v1/allocations/{alloc}/logs",
                     {"logs": [f"live-{i}-{j}" for j in range(10)]})
            except Exception as exc:  # noqa: BLE001
                with lock:
                    errors.append(f"writer: {exc!r}")
            i += 1
            time.sleep(0.5)

    def ws_relay(echo_port):
        _req(port, "POST", f"/api/v1/allocations/{alloc}/proxy",
             {"address": f"127.0.0.1:{echo_port}"})
        while not stop.is_set():
            try:
                s = socket.create_connection(("127.0.0.1", port), timeout=10)
                s.sendall(
                    (f"GET /proxy/{alloc}/kernels/ws HTTP/1.1\r\n"
                     f"Host: 127.0.0.1\r\nUpgrade: websocket\r\n"
                     f"Connection: Upgrade\r\n"
                     f"Sec-WebSocket-Key: c29ha3Nlc3Npb24hIQ==\r\n"
                     f"Sec-WebSocket-Version: 13\r\n\r\n").encode())
                head = b""
                while b"\r\n\r\n" not in head:
                    chunk = s.recv(4096)
                    if not chunk:
                        raise ConnectionError("no 101")
                    head += chunk
                assert b"101" in head.split(b"\r\n", 1)[0]
                for k in range(20):
                    if stop.is_set():
                        break
                    s.sendall(_ws_encode(f"frame-{k}".encode(), mask=True))
                    echoed = _ws_decode(s)
                    assert echoed == f"echo:frame-{k}".encode()
                    with lock:
                        ws_rounds[0] += 1
                    time.sleep(0.25)
                s.close()
            except Exception as exc:  # noqa: BLE001
                if not stop.is_set():
                    with lock:
                        errors.append(f"ws: {exc!r}")
                    time.sleep(1)

    echo = WsEchoServer()
    threads = (
        [threading.Thread(target=vu, args=(i,)) for i in range(VUS)]
        + [threading.Thread(target=follower, args=(i,))
           for i in range(FOLLOWERS)]
        + [threading.Thread(target=log_writer),
           threading.Thread(target=ws_relay, args=(echo.port,))]
    )
    t_start = time.monotonic()
    for t in threads:
        t.start()
    time.sleep(SOAK_SECONDS)
    stop.set()
    for t in threads:
        t.join(timeout=40)
    echo.close()

    with lock:
        all_lat = sorted(lat for _, lat in window_latencies)
        errs = list(errors)

    assert all_lat, "no requests completed"
    fail_rate = len(errs) / (len(all_lat) + len(errs))
    p50 = all_lat[len(all_lat) // 2]
    p95 = all_lat[int(len(all_lat) * 0.95)]

    # per-window p95: the gate must hold THROUGHOUT, not just on average
    windows = {}
    for t_end, lat in window_latencies:
        windows.setdefault(int((t_end - t_start) // 30), []).append(lat)
    window_p95 = {}
    for w, lats in sorted(windows.items()):
        lats.sort()
        if len(lats) >= 20:  # skip ramp slivers
            window_p95[w] = lats[int(len(lats) * 0.95)]

    print(f"\n[soak] {SOAK_SECONDS:.0f}s, {VUS} VUs + {FOLLOWERS} followers"
          f" + WS relay: {len(all_lat)} reqs, p50={p50 * 1000:.1f}ms "
          f"p95={p95 * 1000:.1f}ms, follower_rounds={follower_rounds[0]}, "
          f"ws_frames={ws_rounds[0]}, errors={len(errs)}")
    print(f"[soak] per-30s-window p95: "
          f"{[f'{v * 1000:.0f}ms' for _, v in sorted(window_p95.items())]}")

    assert fail_rate < 0.05, (fail_rate, errs[:5])
    assert p95 < P95_BUDGET_S, f"p95 {p95:.3f}s over {P95_BUDGET_S}s"
    for w, v in window_p95.items():
        assert v < P95_BUDGET_S, f"window {w}: p95 {v:.3f}s over budget"
    # the followers actually tailed (long-poll path exercised, not idle)
    assert follower_rounds[0] >= FOLLOWERS * 2
    # the WS relay stayed live through the soak
    assert ws_rounds[0] >= 20
