"""WebUI: the master serves the static bundle and the app's API surface.

≈ the reference's webui smoke coverage: assets load from the master, content
types are right, path traversal is blocked, and the pages' API calls return
the shapes the views render.
"""
import json
import subprocess
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
WEBUI_DIR = REPO / "webui"


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("webui")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir", str(tmp / "data"),
         "--webui-dir", str(WEBUI_DIR)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/master", timeout=2)
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")
    yield port
    proc.kill()
    proc.wait(timeout=10)


def fetch(port, path):
    resp = urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5)
    return resp.status, resp.headers.get("Content-Type", ""), resp.read()


def test_index_served_at_root(master):
    status, ctype, body = fetch(master, "/")
    assert status == 200 and ctype.startswith("text/html")
    assert b"DCT" in body and b"/ui/app.js" in body


def test_assets_with_content_types(master):
    status, ctype, body = fetch(master, "/ui/app.js")
    assert status == 200 and ctype == "text/javascript"
    assert b"lineChart" in body
    status, ctype, body = fetch(master, "/ui/style.css")
    assert status == 200 and ctype == "text/css"
    assert b"--series-1" in body
    status, ctype, body = fetch(master, "/ui/index.html")
    assert status == 200 and ctype.startswith("text/html")


def test_traversal_blocked(master):
    # encoded and raw traversal must 404, never escape webui/
    for path in ("/ui/..%2F..%2Fbench.py", "/ui/%2e%2e/secrets",
                 "/ui/x/%2e%2e/%2e%2e/bench.py"):
        try:
            status, _, body = fetch(master, path)
        except urllib.error.HTTPError as e:
            status, body = e.code, e.read()
        assert status == 404, (path, body[:100])
        assert b"import" not in body


def test_unknown_asset_404(master):
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(master, "/ui/nope.js")
    assert err.value.code == 404


def test_directory_is_not_an_asset(master):
    # "." resolves to the webui dir itself: must 404, not 200-empty
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(master, "/ui/%2e")
    assert err.value.code == 404


def test_view_api_shapes(master):
    """Each view's fetches return the keys the JS renders."""
    _, _, body = fetch(master, "/api/v1/master")
    info = json.loads(body)
    assert {"version", "cluster_name", "agents"} <= set(info)
    _, _, body = fetch(master, "/api/v1/experiments")
    assert "experiments" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/agents")
    assert "agents" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/job-queue")
    assert "queue" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/tasks")
    assert "tasks" in json.loads(body)
    # admin view fetches
    _, _, body = fetch(master, "/api/v1/users")
    assert "users" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/groups")
    assert "groups" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/rbac/roles")
    assert "roles" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/rbac/assignments")
    assert "assignments" in json.loads(body)


def test_admin_nav_and_view_shipped(master):
    _, _, body = fetch(master, "/ui/index.html")
    assert 'data-nav="admin"' in body.decode()
    _, _, body = fetch(master, "/ui/app.js")
    js = body.decode()
    # admin actions ride the generated client (webui/bindings.js)
    assert "viewAdmin" in js and "assignRole" in js and "unassignRole" in js
    assert "moveJob" in js and "setJobPriority" in js  # queue actions wired


def test_trial_logs_view_shipped(master):
    _, _, body = fetch(master, "/ui/app.js")
    js = body.decode()
    assert "viewTrialLogs" in js
    # the view derives the live leg's allocation id from trial.legs
    assert "trial.legs" in js and "getTaskLogs" in js


def post(port, path, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body or {}).encode(), method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read() or b"{}")


def test_parity_pages_shipped_and_drive_real_api(master):
    """Round-4 parity pages (VERDICT #6): queue, model registry,
    workspaces/projects, trial detail with metrics + profiler charts —
    each present in the bundle and backed by a live API flow."""
    _, _, body = fetch(master, "/ui/app.js")
    js = body.decode()
    for marker in ["viewQueue", "viewModels", "viewModelDetail",
                   "viewWorkspaces", "viewWorkspaceDetail",
                   "viewTrialDetail", "listResourcePools",
                   "getTrialProfiler", "registerModelVersion"]:
        assert marker in js, f"app.js missing {marker}"
    _, _, body = fetch(master, "/ui/index.html")
    index = body.decode()
    for nav in ["queue", "models", "workspaces"]:
        assert f'data-nav="{nav}"' in index

    # the queue page's fetches
    _, _, body = fetch(master, "/api/v1/resource-pools")
    pools = json.loads(body)["resource_pools"]
    assert any(p["is_default"] for p in pools)

    # model registry flow exactly as the page drives it
    post(master, "/api/v1/models", {"name": "ui-model",
                                    "description": "from the ui test"})
    _, _, body = fetch(master, "/api/v1/models/ui-model")
    assert json.loads(body)["model"]["description"] == "from the ui test"

    # workspace detail flow
    ws = post(master, "/api/v1/workspaces", {"name": "ui-ws"})["workspace"]
    post(master, f"/api/v1/workspaces/{ws['id']}/projects",
         {"name": "ui-proj"})
    _, _, body = fetch(master, f"/api/v1/workspaces/{ws['id']}")
    detail = json.loads(body)
    assert [p["name"] for p in detail["projects"]][-1] == "ui-proj"
    assert "experiments" in detail

    # trial detail flow: experiment -> trial -> metrics/profiler/checkpoints
    exp = post(master, "/api/v1/experiments", {"config": {
        "name": "ui-exp", "entrypoint": "x:Y",
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 1}},
        "hyperparameters": {},
    }})["experiment"]
    deadline = time.time() + 30
    trial_id = None
    while time.time() < deadline and trial_id is None:
        _, _, body = fetch(master, f"/api/v1/experiments/{exp['id']}")
        trials = json.loads(body).get("trials") or []
        trial_id = trials[0]["id"] if trials else None
        time.sleep(0.2)
    post(master, f"/api/v1/trials/{trial_id}/metrics",
         {"group": "training", "steps_completed": 1,
          "metrics": {"loss": 1.5}})
    post(master, f"/api/v1/trials/{trial_id}/profiler",
         {"samples": [{"cpu_pct": 12.5, "mem_mb": 100}]})
    _, _, body = fetch(master, f"/api/v1/trials/{trial_id}")
    assert json.loads(body)["trial"]["id"] == trial_id
    _, _, body = fetch(master, f"/api/v1/trials/{trial_id}/metrics?limit=10")
    assert json.loads(body)["metrics"][-1]["metrics"]["loss"] == 1.5
    _, _, body = fetch(master, f"/api/v1/trials/{trial_id}/profiler?limit=10")
    assert json.loads(body)["samples"][-1]["cpu_pct"] == 12.5
    _, _, body = fetch(master, f"/api/v1/trials/{trial_id}/checkpoints")
    assert "checkpoints" in json.loads(body)
    post(master, f"/api/v1/experiments/{exp['id']}/kill")
