"""WebUI: the master serves the static bundle and the app's API surface.

≈ the reference's webui smoke coverage: assets load from the master, content
types are right, path traversal is blocked, and the pages' API calls return
the shapes the views render.
"""
import json
import subprocess
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
WEBUI_DIR = REPO / "webui"


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not MASTER_BIN.exists():
        r = subprocess.run(["make", "-C", str(MASTER_DIR)],
                           capture_output=True)
        if r.returncode != 0:
            pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("webui")

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir", str(tmp / "data"),
         "--webui-dir", str(WEBUI_DIR)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/v1/master", timeout=2)
            break
        except Exception:
            time.sleep(0.2)
    else:
        proc.kill()
        pytest.fail("master did not come up")
    yield port
    proc.kill()
    proc.wait(timeout=10)


def fetch(port, path):
    resp = urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5)
    return resp.status, resp.headers.get("Content-Type", ""), resp.read()


def test_index_served_at_root(master):
    status, ctype, body = fetch(master, "/")
    assert status == 200 and ctype.startswith("text/html")
    assert b"DCT" in body and b"/ui/app.js" in body


def test_assets_with_content_types(master):
    status, ctype, body = fetch(master, "/ui/app.js")
    assert status == 200 and ctype == "text/javascript"
    assert b"lineChart" in body
    status, ctype, body = fetch(master, "/ui/style.css")
    assert status == 200 and ctype == "text/css"
    assert b"--series-1" in body
    status, ctype, body = fetch(master, "/ui/index.html")
    assert status == 200 and ctype.startswith("text/html")


def test_traversal_blocked(master):
    # encoded and raw traversal must 404, never escape webui/
    for path in ("/ui/..%2F..%2Fbench.py", "/ui/%2e%2e/secrets",
                 "/ui/x/%2e%2e/%2e%2e/bench.py"):
        try:
            status, _, body = fetch(master, path)
        except urllib.error.HTTPError as e:
            status, body = e.code, e.read()
        assert status == 404, (path, body[:100])
        assert b"import" not in body


def test_unknown_asset_404(master):
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(master, "/ui/nope.js")
    assert err.value.code == 404


def test_directory_is_not_an_asset(master):
    # "." resolves to the webui dir itself: must 404, not 200-empty
    with pytest.raises(urllib.error.HTTPError) as err:
        fetch(master, "/ui/%2e")
    assert err.value.code == 404


def test_view_api_shapes(master):
    """Each view's fetches return the keys the JS renders."""
    _, _, body = fetch(master, "/api/v1/master")
    info = json.loads(body)
    assert {"version", "cluster_name", "agents"} <= set(info)
    _, _, body = fetch(master, "/api/v1/experiments")
    assert "experiments" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/agents")
    assert "agents" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/job-queue")
    assert "queue" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/tasks")
    assert "tasks" in json.loads(body)
    # admin view fetches
    _, _, body = fetch(master, "/api/v1/users")
    assert "users" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/groups")
    assert "groups" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/rbac/roles")
    assert "roles" in json.loads(body)
    _, _, body = fetch(master, "/api/v1/rbac/assignments")
    assert "assignments" in json.loads(body)


def test_admin_nav_and_view_shipped(master):
    _, _, body = fetch(master, "/ui/index.html")
    assert 'data-nav="admin"' in body.decode()
    _, _, body = fetch(master, "/ui/app.js")
    js = body.decode()
    # admin actions ride the generated client (webui/bindings.js)
    assert "viewAdmin" in js and "assignRole" in js and "unassignRole" in js
    assert "moveJob" in js and "setJobPriority" in js  # queue actions wired


def test_trial_logs_view_shipped(master):
    _, _, body = fetch(master, "/ui/app.js")
    js = body.decode()
    assert "viewTrialLogs" in js
    # the view derives the live leg's allocation id from trial.legs
    assert "trial.legs" in js and "getTaskLogs" in js
