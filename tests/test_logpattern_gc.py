"""Log-pattern policies + checkpoint GC, e2e against master+agent.

≈ the reference's logpattern behavior (master/internal/logpattern →
trial.go:381 blocked nodes, trial.go:184 non-retryable classification) and
checkpoint GC policy (checkpoint_gc.go:27 + exec/gc_checkpoints.py:97).
"""
import os
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
MASTER_DIR = REPO / "determined_clone_tpu" / "master"
MASTER_BIN = MASTER_DIR / "build" / "dct-master"
AGENT_BIN = MASTER_DIR / "build" / "dct-agent"

# fails every leg after printing a recognizable poison line
FAILING_TRIAL = '''
import sys

import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training import JaxTrial


class Trial(JaxTrial):
    def initial_params(self, rng):
        print("DCT-POISON: device wedged", flush=True)
        sys.stdout.flush()
        raise RuntimeError("boom")

    def optimizer(self):
        return optax.sgd(0.1)

    def loss(self, params, batch, rng):
        return jnp.zeros(()), {}

    def training_data(self):
        yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return []

    @property
    def global_batch_size(self):
        return 2
'''

# checkpoints every 2 batches -> several checkpoints per run
CKPT_TRIAL = '''
import jax.numpy as jnp
import numpy as np
import optax

from determined_clone_tpu.training import JaxTrial


class Trial(JaxTrial):
    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.2)

    def loss(self, params, batch, rng):
        return (params["w"] - 2.0) ** 2, {}

    def training_data(self):
        for _ in range(64):
            yield np.zeros((2, 1), np.float32)

    def validation_data(self):
        return [np.zeros((2, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 2
'''


def build_binaries():
    if MASTER_BIN.exists() and AGENT_BIN.exists():
        return True
    r = subprocess.run(["make", "-C", str(MASTER_DIR)], capture_output=True)
    return r.returncode == 0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master/agent build unavailable")
    tmp = tmp_path_factory.mktemp("lpgc")
    workdir = tmp / "agent-work"
    workdir.mkdir()
    (workdir / "failing_def.py").write_text(FAILING_TRIAL)
    (workdir / "ckpt_def.py").write_text(CKPT_TRIAL)

    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": str(REPO),
        "DCT_AGENT_SLOTS": "1",
        "DCT_AGENT_TOPOLOGY": "v5e-1",
    }
    master = subprocess.Popen(
        [str(MASTER_BIN), "--port", str(port), "--data-dir",
         str(tmp / "master-data")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )
    agent = subprocess.Popen(
        [str(AGENT_BIN), "--master-port", str(port), "--id", "lpgc-agent",
         "--work-dir", str(workdir)],
        cwd=str(workdir),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    )

    from determined_clone_tpu.api.client import MasterSession

    session = MasterSession("127.0.0.1", port, timeout=10, retries=20)
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if session.list_agents():
                break
        except Exception:
            time.sleep(0.3)
    else:
        master.kill()
        agent.kill()
        pytest.fail("cluster did not come up")

    yield {"session": session, "tmp": tmp, "port": port}

    agent.kill()
    master.kill()
    agent.wait(timeout=10)
    master.wait(timeout=10)


def wait_for(predicate, timeout=120, interval=0.5, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def base_config(cluster, name, entrypoint, **over):
    cfg = {
        "name": name,
        "entrypoint": entrypoint,
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 8}},
        "resources": {"slots_per_trial": 1},
        "scheduling_unit": 2,
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(cluster["tmp"] / "ckpts")},
        "hyperparameters": {},
        "max_restarts": 3,
    }
    cfg.update(over)
    return cfg


def test_bad_log_pattern_rejected_at_submission(cluster):
    from determined_clone_tpu.api.client import MasterError

    with pytest.raises(MasterError) as err:
        cluster["session"].create_experiment(base_config(
            cluster, "lp-bad", "failing_def:Trial",
            log_policies=[{"pattern": "DCT-POISON(",
                           "action": {"type": "cancel_retries"}}],
        ))
    assert err.value.status == 400


def test_cancel_retries_log_policy(cluster):
    """Poison line → cancel_retries → exactly ONE leg despite max_restarts=3."""
    session = cluster["session"]
    exp = session.create_experiment(base_config(
        cluster, "lp-cancel", "failing_def:Trial",
        log_policies=[{"pattern": r"DCT-POISON",
                       "action": {"type": "cancel_retries"}}],
    ))
    detail = wait_for(
        lambda: (lambda d: d if d["experiment"]["state"] == "ERRORED" else None)(
            session.get_experiment(exp["id"])),
        desc="experiment errored", timeout=120,
    )
    trial = detail["trials"][0]
    assert trial["state"] == "ERRORED"
    assert trial["no_retries"] is True
    # only the first leg ran: restarts counted once, no retry allocations
    assert trial["restarts"] == 1


def test_exclude_node_log_policy_blocks_agent(cluster):
    """Poison line → exclude_node → the only agent is blocklisted, so the
    retry leg can never schedule (stays QUEUED)."""
    session = cluster["session"]
    exp = session.create_experiment(base_config(
        cluster, "lp-exclude", "failing_def:Trial",
        log_policies=[{"pattern": r"DCT-POISON",
                       "action": {"type": "exclude_node"}}],
    ))

    def blocked():
        agents = session.list_agents()
        key = f"exp-{exp['id']}"
        return agents[0] if key in agents[0].get("blocked_by", []) else None

    wait_for(blocked, desc="agent blocklisted", timeout=120)

    # the retry allocation exists but cannot fit anywhere
    def retry_queued():
        detail = session.get_experiment(exp["id"])
        t = detail["trials"][0]
        return t if t["restarts"] >= 1 and t["state"] == "QUEUED" else None

    wait_for(retry_queued, desc="retry leg starved by blocklist", timeout=60)
    session.kill_experiment(exp["id"])


def test_checkpoint_gc_policy(cluster):
    """save_trial_latest=1: after completion only the newest checkpoint
    survives; older ones are registry-deleted AND removed from storage by
    the GC task."""
    session = cluster["session"]
    exp = session.create_experiment(base_config(
        cluster, "gc-exp", "ckpt_def:Trial",
        min_checkpoint_period={"batches": 2},
        checkpoint_storage={
            "type": "shared_fs",
            "host_path": str(cluster["tmp"] / "ckpts"),
            "save_trial_latest": 1,
            "save_trial_best": 0,
        },
        max_restarts=0,
    ))
    wait_for(
        lambda: session.get_experiment(exp["id"])["experiment"]["state"]
        == "COMPLETED",
        desc="experiment completion", timeout=120,
    )
    all_ckpts = session.get(
        f"/api/v1/experiments/{exp['id']}/checkpoints")["checkpoints"]
    # live records exclude deleted; exactly one survivor
    assert len(all_ckpts) == 1, all_ckpts
    survivor = all_ckpts[0]["uuid"]

    # GC task ran and the storage dir only holds the survivor
    ckpt_root = cluster["tmp"] / "ckpts"

    def storage_clean():
        dirs = {p.name for p in ckpt_root.iterdir() if p.is_dir()}
        mine = {d for d in dirs}
        return mine if survivor in mine else None

    wait_for(storage_clean, desc="storage has survivor", timeout=60)

    def gc_done():
        tasks = [t for t in session.list_tasks("command")
                 if t["name"].startswith(f"checkpoint-gc-exp-{exp['id']}")]
        return tasks if tasks and all(
            t["state"] in ("COMPLETED", "ERRORED") for t in tasks) else None

    tasks = wait_for(gc_done, desc="gc task finished", timeout=60)
    assert tasks[0]["state"] == "COMPLETED"

    # storage: survivor present, at least one deleted uuid absent
    deleted_uuid_logs = session.task_logs(tasks[0]["id"])
    joined = "\n".join(l.get("log", "") for l in deleted_uuid_logs)
    assert "deleted checkpoint" in joined
    assert (ckpt_root / survivor).exists()
    for line in joined.splitlines():
        if line.startswith("deleted checkpoint "):
            gone = line.split()[-1]
            assert not (ckpt_root / gone).exists()
