"""Model-family tests — tiny deterministic models, the reference's fixture
strategy (harness/tests/experiment/fixtures/pytorch_onevar_model.py etc.)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from determined_clone_tpu.models import bert, gpt, mlp, mnist_cnn, resnet
from determined_clone_tpu.ops import attention
from determined_clone_tpu.parallel import MeshSpec, make_mesh, shard_put
from determined_clone_tpu.parallel.sharding import batch_spec


class TestAttention:
    def test_blockwise_matches_full(self):
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        B, T, H, D = 2, 64, 4, 16
        q = jax.random.normal(kq, (B, T, H, D))
        k = jax.random.normal(kk, (B, T, H, D))
        v = jax.random.normal(kv, (B, T, H, D))
        full = attention.mha(q, k, v, causal=True)
        blocked = attention.causal_blockwise_attention(q, k, v, block_size=16)
        np.testing.assert_allclose(np.asarray(full), np.asarray(blocked),
                                   atol=1e-5, rtol=1e-5)

    def test_causality(self):
        key = jax.random.PRNGKey(1)
        B, T, H, D = 1, 32, 2, 8
        q, k, v = (jax.random.normal(kk, (B, T, H, D))
                   for kk in jax.random.split(key, 3))
        out1 = attention.mha(q, k, v, causal=True)
        # perturbing the future must not change the past
        k2 = k.at[:, T // 2:].set(0.0)
        v2 = v.at[:, T // 2:].set(0.0)
        out2 = attention.mha(q, k2, v2, causal=True)
        np.testing.assert_allclose(np.asarray(out1[:, : T // 2]),
                                   np.asarray(out2[:, : T // 2]), atol=1e-5)

    def test_rotary_preserves_norm(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (1, 16, 2, 8))
        rot = attention.rotary_embedding(x, jnp.arange(16))
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(rot), axis=-1),
            rtol=1e-5,
        )


class TestMLP:
    def test_shapes_and_grad(self):
        cfg = mlp.MLPConfig(in_dim=16, hidden_dims=(8,), n_classes=4)
        params = mlp.init(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (5, 16))
        y = jnp.array([0, 1, 2, 3, 0])
        logits = mlp.apply(params, cfg, x)
        assert logits.shape == (5, 4)
        g = jax.grad(mlp.loss_fn)(params, cfg, x, y)
        assert jax.tree.structure(g) == jax.tree.structure(params)

    def test_learns_linearly_separable(self):
        cfg = mlp.MLPConfig(in_dim=2, hidden_dims=(16,), n_classes=2)
        params = mlp.init(jax.random.PRNGKey(0), cfg)
        key = jax.random.PRNGKey(42)
        x = jax.random.normal(key, (256, 2))
        y = (x[:, 0] > 0).astype(jnp.int32)

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(mlp.loss_fn)(p, cfg, x, y)
            return jax.tree.map(lambda w, gw: w - 0.5 * gw, p, g), loss

        for _ in range(60):
            params, loss = step(params)
        assert float(loss) < 0.1


class TestMnistCNN:
    def test_forward(self):
        cfg = mnist_cnn.MnistCNNConfig(n_filters_1=4, n_filters_2=8)
        params = mnist_cnn.init(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (3, 28, 28, 1))
        logits = mnist_cnn.apply(params, cfg, x)
        assert logits.shape == (3, 10)
        flat = mnist_cnn.apply(params, cfg, x.reshape(3, 784))
        np.testing.assert_allclose(np.asarray(logits), np.asarray(flat), atol=1e-6)

    def test_dropout_only_when_training(self):
        cfg = mnist_cnn.MnistCNNConfig(n_filters_1=4, n_filters_2=8)
        params = mnist_cnn.init(jax.random.PRNGKey(0), cfg)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 28, 28, 1))
        key = jax.random.PRNGKey(5)
        eval1 = mnist_cnn.apply(params, cfg, x, training=False, dropout_key=key)
        eval2 = mnist_cnn.apply(params, cfg, x, training=False, dropout_key=key)
        np.testing.assert_allclose(np.asarray(eval1), np.asarray(eval2))
        tr1 = mnist_cnn.apply(params, cfg, x, training=True, dropout_key=key)
        tr2 = mnist_cnn.apply(
            params, cfg, x, training=True, dropout_key=jax.random.PRNGKey(6)
        )
        assert not np.allclose(np.asarray(tr1), np.asarray(tr2))


class TestResNet:
    def setup_method(self):
        self.cfg = resnet.ResNetConfig.tiny()
        self.params = resnet.init(jax.random.PRNGKey(0), self.cfg)

    def test_forward_shape_and_dtype(self):
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))
        logits = resnet.apply(self.params, self.cfg, x)
        assert logits.shape == (2, self.cfg.n_classes)
        assert logits.dtype == jnp.float32

    def test_depth_variants_param_structure(self):
        # one bottleneck param group per block, depths from the variant table
        n_blocks = sum(self.cfg.stage_blocks)
        import re
        block_keys = [k for k in self.params if re.fullmatch(r"s\d+b\d+", k)]
        assert len(block_keys) == n_blocks
        with pytest.raises(ValueError):
            resnet.ResNetConfig(depth=37).stage_blocks

    def test_grad_structure(self):
        x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 32, 3))
        y = jnp.array([0, 1])
        g = jax.grad(resnet.loss_fn)(self.params, self.cfg, x, y)
        assert jax.tree.structure(g) == jax.tree.structure(self.params)
        # every leaf receives gradient signal (no dead branches): a
        # disconnected block would produce exactly-zero grads
        norms = [float(jnp.abs(l).sum()) for l in jax.tree.leaves(g)]
        assert all(np.isfinite(n) and n > 0 for n in norms)

    def test_loss_decreases(self):
        x = jax.random.normal(jax.random.PRNGKey(3), (8, 32, 32, 3))
        y = jax.random.randint(jax.random.PRNGKey(4), (8,), 0,
                               self.cfg.n_classes)

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(resnet.loss_fn)(p, self.cfg, x, y)
            return jax.tree.map(lambda w, gw: w - 0.05 * gw, p, g), loss

        params = self.params
        params, first = step(params)
        for _ in range(10):
            params, loss = step(params)
        assert float(loss) < float(first)

    def test_sharded_forward_matches_single(self):
        # dp+fsdp data parallelism with the auto-ZeRO-3 fallback rules
        mesh = make_mesh(MeshSpec(dp=4, fsdp=2))
        x = jax.random.normal(jax.random.PRNGKey(5), (8, 32, 32, 3))
        expect = resnet.apply(self.params, self.cfg, x)
        from determined_clone_tpu.parallel.sharding import ShardingRules

        shardings = ShardingRules().shardings_for(self.params, mesh)
        sp = shard_put(self.params, shardings)
        sx = shard_put(x, NamedSharding(mesh, batch_spec(extra_dims=3)))
        got = jax.jit(lambda p, v: resnet.apply(p, self.cfg, v))(sp, sx)
        np.testing.assert_allclose(np.asarray(expect), np.asarray(got),
                                   atol=1e-4, rtol=1e-4)


class TestBert:
    def setup_method(self):
        self.cfg = bert.BertConfig.tiny()
        self.params = bert.init(jax.random.PRNGKey(0), self.cfg)

    def test_classify_shape_and_dtype(self):
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = bert.classify(self.params, self.cfg, tokens)
        assert logits.shape == (2, self.cfg.n_classes)
        assert logits.dtype == jnp.float32

    def test_mlm_logits_tied_to_embedding(self):
        tokens = jnp.zeros((1, 8), jnp.int32)
        logits = bert.mlm_logits(self.params, self.cfg, tokens)
        assert logits.shape == (1, 8, self.cfg.vocab_size)
        # perturbing the embedding table must move the MLM projection too
        p2 = jax.tree.map(lambda x: x, self.params)
        p2["embed"] = {"table": self.params["embed"]["table"] + 0.1}
        logits2 = bert.mlm_logits(p2, self.cfg, tokens)
        assert not np.allclose(np.asarray(logits), np.asarray(logits2))

    def test_bidirectional_not_causal(self):
        # flipping a LATER token must change EARLIER positions (encoder,
        # unlike the GPT causality test)
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)
        t2 = t1.at[0, -1].set((t1[0, -1] + 1) % 256)
        e1 = bert.encode(self.params, self.cfg, t1)
        e2 = bert.encode(self.params, self.cfg, t2)
        assert not np.allclose(np.asarray(e1[:, 0]), np.asarray(e2[:, 0]),
                               atol=1e-6)

    def test_pad_mask_blocks_padding(self):
        # garbage in padded positions must not leak into real tokens
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 16), 0, 256)
        mask = jnp.concatenate(
            [jnp.ones((1, 8), jnp.float32), jnp.zeros((1, 8), jnp.float32)], 1)
        garbage = tokens.at[0, 8:].set(255)
        e1 = bert.encode(self.params, self.cfg, tokens, pad_mask=mask)
        e2 = bert.encode(self.params, self.cfg, garbage, pad_mask=mask)
        np.testing.assert_allclose(np.asarray(e1[:, :8]),
                                   np.asarray(e2[:, :8]), atol=1e-5)

    def test_classify_loss_decreases(self):
        tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, 256)
        labels = jax.random.randint(jax.random.PRNGKey(4), (8,), 0, 2)

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(bert.classify_loss)(
                p, self.cfg, tokens, labels)
            return jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g), loss

        params = self.params
        params, first = step(params)
        for _ in range(10):
            params, loss = step(params)
        assert float(loss) < float(first)

    def test_mlm_loss_masks_positions(self):
        tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 16), 0, 256)
        targets = jax.random.randint(jax.random.PRNGKey(6), (2, 16), 0, 256)
        mask = jnp.zeros((2, 16)).at[:, :4].set(1.0)
        loss = bert.mlm_loss(self.params, self.cfg, tokens, targets, mask)
        # changing targets at UNMASKED positions must not move the loss
        targets2 = targets.at[:, 8:].set(0)
        loss2 = bert.mlm_loss(self.params, self.cfg, tokens, targets2, mask)
        np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-6)

    def test_sharded_forward_matches_single(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 16), 0, 256)
        expect = bert.classify(self.params, self.cfg, tokens)
        shardings = bert.BERT_SHARDING_RULES.shardings_for(self.params, mesh)
        sp = shard_put(self.params, shardings)
        st = shard_put(tokens, NamedSharding(mesh, batch_spec(extra_dims=1)))
        got = jax.jit(lambda p, t: bert.classify(p, self.cfg, t))(sp, st)
        np.testing.assert_allclose(np.asarray(expect), np.asarray(got),
                                   atol=2e-2, rtol=2e-2)


class TestGPT:
    def setup_method(self):
        self.cfg = gpt.GPTConfig.tiny()
        self.params = gpt.init(jax.random.PRNGKey(0), self.cfg)

    def test_stacked_blocks_shape(self):
        qkv = self.params["blocks"]["attn_qkv"]["kernel"]
        assert qkv.shape == (2, 64, 192)  # [L, D, 3D]

    def test_forward_shape_and_dtype(self):
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = gpt.apply(self.params, self.cfg, tokens)
        assert logits.shape == (2, 16, self.cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        t1 = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 256)
        t2 = t1.at[0, -1].set((t1[0, -1] + 1) % 256)
        l1 = gpt.apply(self.params, self.cfg, t1)
        l2 = gpt.apply(self.params, self.cfg, t2)
        np.testing.assert_allclose(np.asarray(l1[:, :-1]), np.asarray(l2[:, :-1]),
                                   atol=1e-4)

    def test_loss_decreases(self):
        tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 32), 0, 256)
        inputs, targets = tokens[:, :-1], tokens[:, 1:]

        @jax.jit
        def step(p):
            loss, g = jax.value_and_grad(gpt.loss_fn)(p, self.cfg, inputs, targets)
            return jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g), loss

        params = self.params
        params, first = step(params)
        for _ in range(10):
            params, loss = step(params)
        assert float(loss) < float(first)

    def test_sharded_forward_matches_single(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        tokens = jax.random.randint(jax.random.PRNGKey(4), (8, 16), 0, 256)
        expect = gpt.apply(self.params, self.cfg, tokens)

        shardings = gpt.GPT_SHARDING_RULES.shardings_for(self.params, mesh)
        sharded_params = shard_put(self.params, shardings)
        sharded_tokens = shard_put(
            tokens, NamedSharding(mesh, batch_spec(extra_dims=1))
        )

        @jax.jit
        def fwd(p, t):
            return gpt.apply(p, self.cfg, t)

        got = fwd(sharded_params, sharded_tokens)
        np.testing.assert_allclose(np.asarray(expect), np.asarray(got),
                                   atol=2e-2, rtol=2e-2)

    def test_blockwise_attention_config(self):
        cfg = gpt.GPTConfig(vocab_size=256, n_layers=2, d_model=64, n_heads=4,
                            d_ff=128, max_seq_len=128, remat=False,
                            blockwise_attention=True, attention_block_size=16)
        tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 32), 0, 256)
        base = gpt.apply(self.params, self.cfg, tokens)
        blocked = gpt.apply(self.params, cfg, tokens)
        # bf16 compute: different summation order → small noise
        np.testing.assert_allclose(np.asarray(base), np.asarray(blocked),
                                   atol=1e-2, rtol=1e-2)

    def test_dropout_active_only_in_training(self):
        cfg = gpt.GPTConfig(vocab_size=256, n_layers=2, d_model=64, n_heads=4,
                            d_ff=128, max_seq_len=128, remat=False, dropout=0.5)
        params = gpt.init(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((1, 8), jnp.int32)
        key = jax.random.PRNGKey(9)
        e1 = gpt.apply(params, cfg, tokens)
        e2 = gpt.apply(params, cfg, tokens, training=False, dropout_key=key)
        np.testing.assert_allclose(np.asarray(e1), np.asarray(e2))
        t1 = gpt.apply(params, cfg, tokens, training=True, dropout_key=key)
        assert not np.allclose(np.asarray(e1), np.asarray(t1))

    def test_param_count(self):
        n = gpt.param_count(self.params)
        assert n > 50_000  # tiny but real
