"""Model hub: HF transformers adapter trains a Flax GPT-2 through the
Trainer (offline, from_config — no weight downloads).

≈ the reference's model_hub tests (HF trials driven through the trial
controller, model_hub/tests/)."""
import contextlib

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from determined_clone_tpu import core
from determined_clone_tpu.config.experiment import ExperimentConfig
from determined_clone_tpu.model_hub import HFCausalLMTrial, lm_batches
from determined_clone_tpu.training import Trainer, TrialContext


class TinyGPT2Trial(HFCausalLMTrial):
    def model_config(self):
        return transformers.GPT2Config(
            n_layer=2, n_embd=32, n_head=2, vocab_size=64, n_positions=32)

    def training_data(self):
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, 64, size=4096)
        yield from lm_batches(tokens, self.global_batch_size, seq_len=16)

    def validation_data(self):
        rng = np.random.RandomState(1)
        tokens = rng.randint(0, 64, size=512)
        return list(lm_batches(tokens, self.global_batch_size, seq_len=16))

    @property
    def global_batch_size(self):
        return 4


def test_lm_batches_shapes():
    tokens = np.arange(1000)
    batches = list(lm_batches(tokens, batch_size=3, seq_len=8))
    assert all(b.shape == (3, 9) for b in batches)
    assert batches[0][0, 0] == 0
    # windows shift by seq_len with one-token overlap for labels
    assert batches[0][1, 0] == 8
    assert batches[0][0, 8] == batches[0][1, 0]


def test_hf_trial_trains(tmp_path):
    config = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 6}},
        "scheduling_unit": 3,
        "resources": {"slots_per_trial": 1},
    })
    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(
            core.init(config=config, storage_path=str(tmp_path)))
        tctx = TrialContext(
            config=config,
            hparams={"learning_rate": 1e-3, "warmup_steps": 2},
            core=ctx,
        )
        trial = TinyGPT2Trial(tctx)
        result = Trainer(trial).fit()

    assert result["batches_trained"] == 6
    val = result["last_validation"]
    assert "loss" in val and "perplexity" in val
    assert np.isfinite(val["loss"])
    # random 64-token LM starts near ln(64)≈4.16; a few steps should move it
    assert val["loss"] < 4.5


def test_from_pretrained_local_path(tmp_path):
    """The pretrained_name() path works offline with a saved checkpoint —
    the from_pretrained branch the reference's HF trials rely on, exercised
    via save_pretrained -> load from a local directory (no downloads)."""
    saved = tmp_path / "tiny-gpt2"
    base = transformers.FlaxAutoModelForCausalLM.from_config(
        transformers.GPT2Config(n_layer=1, n_embd=16, n_head=2,
                                vocab_size=32, n_positions=16))
    base.save_pretrained(str(saved))

    class PretrainedTrial(TinyGPT2Trial):
        def pretrained_name(self):
            return str(saved)

    config = ExperimentConfig.from_dict({
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 2}},
        "scheduling_unit": 2,
        "resources": {"slots_per_trial": 1},
    })
    with contextlib.ExitStack() as stack:
        ctx = stack.enter_context(
            core.init(config=config, storage_path=str(tmp_path / "ck")))
        trial = PretrainedTrial(TrialContext(
            config=config, hparams={"learning_rate": 1e-3}, core=ctx))
        # the loaded model IS the saved one, weights and all (build_model
        # does not consume the wrapper's params the way initial_params does)
        import numpy as _np

        loaded = trial.build_model().params
        _np.testing.assert_array_equal(
            _np.asarray(loaded["transformer"]["wte"]["embedding"]),
            _np.asarray(base.params["transformer"]["wte"]["embedding"]))
        result = Trainer(trial).fit()
    assert result["batches_trained"] == 2
