"""det deploy gcp / gke: dry-run plans and manifest generation.

≈ the reference's deploy-tooling unit tests (harness/tests/determined/
deploy): no cloud calls — the dry-run runner records the exact argv plan.
"""
import json

from determined_clone_tpu.deploy import (
    DryRunRunner,
    gcp_down,
    gcp_up,
    gke_down,
    gke_manifests,
    gke_up,
)


def test_gcp_up_plan():
    plan = gcp_up(project="proj-1", zone="us-east5-b",
                  accelerator_type="v5litepod-16", n_agents=2,
                  auth_required=True)
    assert plan["dry_run"] is True
    cmds = plan["commands"]
    # one master VM, one firewall rule, two TPU-VM agents
    assert sum("instances create" in c for c in cmds) == 1
    assert sum("firewall-rules create" in c for c in cmds) == 1
    tpu_creates = [c for c in cmds if "tpus tpu-vm create" in c]
    assert len(tpu_creates) == 2
    assert all("--accelerator-type v5litepod-16" in c for c in tpu_creates)
    assert all("--zone us-east5-b" in c for c in tpu_creates)
    # agents' startup script points at the master by name and pool
    assert all("--master-host dct-master" in c for c in tpu_creates)
    master_cmd = next(c for c in cmds if "instances create" in c)
    assert "--auth-required" in master_cmd
    assert plan["agents"] == ["dct-agent-0", "dct-agent-1"]


def test_gcp_down_plan_mirrors_up():
    plan = gcp_down(project="proj-1", zone="us-east5-b", n_agents=2)
    cmds = plan["commands"]
    assert sum("tpus tpu-vm delete" in c for c in cmds) == 2
    assert sum("instances delete" in c for c in cmds) == 1
    assert sum("firewall-rules delete" in c for c in cmds) == 1


def test_gke_manifests_wire_kubernetes_rm():
    docs = gke_manifests(namespace="prod", image="gcr.io/x/dct:1",
                         slots_per_pod=4, auth_required=True)
    by_kind = {}
    for d in docs:
        by_kind.setdefault(d["kind"], []).append(d)
    assert set(by_kind) == {"Namespace", "ServiceAccount", "Role",
                            "RoleBinding", "Deployment", "Service"}
    dep = by_kind["Deployment"][0]
    cmd = dep["spec"]["template"]["spec"]["containers"][0]["command"]
    assert "--rm" in cmd and cmd[cmd.index("--rm") + 1] == "kubernetes"
    assert "--kube-live" in cmd
    assert "--auth-required" in cmd
    assert cmd[cmd.index("--kube-slots-per-pod") + 1] == "4"
    # the RM's service account can manage pods
    rules = by_kind["Role"][0]["rules"][0]
    assert "pods" in rules["resources"] and "create" in rules["verbs"]
    # service name matches the --kube-master-host the pods will dial
    assert by_kind["Service"][0]["metadata"]["name"] == "dct-master"
    assert cmd[cmd.index("--kube-master-host") + 1] == "dct-master"
    # everything namespaced lands in the requested namespace
    for d in docs:
        if d["kind"] != "Namespace":
            assert d["metadata"]["namespace"] == "prod"


def test_gke_up_writes_manifests(tmp_path):
    out = tmp_path / "manifests.json"
    plan = gke_up(project="p", zone="z", manifest_path=str(out),
                  accelerator_type="v5litepod-8", tpu_topology="2x4")
    assert plan["dry_run"] is True
    docs = json.loads(out.read_text())
    assert any(d["kind"] == "Deployment" for d in docs)
    cmds = plan["commands"]
    assert any("node-pools create" in c and "--tpu-topology 2x4" in c
               for c in cmds)
    assert any(f"kubectl apply -f {out}" in c for c in cmds)


def test_gke_down_plan():
    plan = gke_down(project="p", zone="z")
    cmds = plan["commands"]
    assert any("delete namespace dct" in c for c in cmds)
    assert any("node-pools delete" in c for c in cmds)


def test_custom_runner_receives_argv():
    runner = DryRunRunner()
    gcp_up(project="p", zone="z", runner=runner)
    assert all(isinstance(argv, list) and argv[0] == "gcloud"
               for argv in runner.commands)
