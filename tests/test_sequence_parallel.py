"""Sequence-parallel attention: ring (ppermute) and Ulysses (all-to-all).

Both schemes shard the sequence axis over an `sp` mesh axis inside
shard_map and must match full (unsharded) mha numerically — exceeding the
reference, which has no sequence parallelism at all (SURVEY.md §5.7).
Runs on the virtual 8-device CPU mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from determined_clone_tpu.ops.attention import (
    mha,
    ring_attention,
    ulysses_attention,
)

SP = 4
B, T, H, D = 2, 256, 8, 32


@pytest.fixture(scope="module")
def mesh():
    devs = np.array(jax.devices()[:SP]).reshape(SP)
    return Mesh(devs, ("sp",))


@pytest.fixture(scope="module")
def qkv():
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(jax.random.normal(k, (B, T, H, D), jnp.float32) for k in ks)


def test_ring_matches_full(mesh, qkv):
    q, k, v = qkv
    spec = P(None, "sp")

    def local(q, k, v):
        idx = jax.lax.axis_index("sp")
        return ring_attention(q, k, v, axis_name="sp", axis_index=idx,
                              axis_size=SP)

    f = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                  out_specs=spec)
    out = jax.jit(f)(q, k, v)
    ref = mha(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_matches_full(mesh, qkv):
    q, k, v = qkv
    spec = P(None, "sp")

    def local(q, k, v):
        return ulysses_attention(q, k, v, axis_name="sp", causal=True)

    f = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                  out_specs=spec)
    out = jax.jit(f)(q, k, v)
    ref = mha(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_gradients_match_full(mesh, qkv):
    q, k, v = qkv
    spec = P(None, "sp")

    def sp_loss(q, k, v):
        f = shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
        return (f(q, k, v) ** 2).sum()

    def ref_loss(q, k, v):
        return (mha(q, k, v, causal=True) ** 2).sum()

    g_sp = jax.jit(jax.grad(sp_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_sp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-3)


def test_ulysses_requires_divisible_heads(mesh):
    # H=6 not divisible by sp=4: all_to_all must reject, not silently skew
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(key, (B, T, 6, D)) for key in ks)
    spec = P(None, "sp")
    f = shard_map(lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp"),
                  mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    with pytest.raises(Exception):
        jax.jit(f)(q, k, v)
