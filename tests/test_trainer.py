"""Trainer loop tests with tiny deterministic trials — the reference's
onevar/no_op fixture strategy (harness/tests/experiment/fixtures/)."""
import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_clone_tpu import core
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.parallel import MeshSpec, ShardingRules, make_mesh
from determined_clone_tpu.training import JaxTrial, Trainer, TrialContext
from determined_clone_tpu.utils.data import batch_iterator, synthetic_mnist


class OneVarTrial(JaxTrial):
    """loss = (w - 3)^2 — analytically checkable (reference:
    harness/tests/experiment/fixtures/pytorch_onevar_model.py)."""

    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(self.context.get_hparam("lr", 0.1))

    def loss(self, params, batch, rng):
        del batch, rng
        loss = (params["w"] - 3.0) ** 2
        return loss, {"w": params["w"]}

    def training_data(self):
        for _ in range(64):
            yield np.zeros((4, 1), np.float32)

    def validation_data(self):
        return [np.zeros((4, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 4


def make_context(tmp_path, config_dict=None, hparams=None, mesh=None):
    cfg = ExperimentConfig.from_dict(config_dict or {
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": 30}},
        "scheduling_unit": 10,
        "checkpoint_storage": {"type": "shared_fs", "host_path": str(tmp_path)},
    })
    core_ctx_mgr = core.init(config=cfg, trial_id=1)
    core_ctx = core_ctx_mgr.__enter__()
    if mesh is None:
        mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    return TrialContext(config=cfg, hparams=hparams or {}, core=core_ctx,
                        mesh=mesh), core_ctx_mgr


class TestTrainerOneVar:
    def test_converges_and_reports(self, tmp_path):
        ctx, mgr = make_context(tmp_path)
        try:
            backend = ctx.core.train._backend
            result = Trainer(OneVarTrial(ctx)).fit()
            assert result["batches_trained"] == 30
            # w -> 3.0 under SGD on (w-3)^2
            final_w = [r for r in backend.records if r["group"] == "training"][-1][
                "metrics"]["w"]
            assert abs(final_w - 3.0) < 0.1
            groups = {r["group"] for r in backend.records}
            assert "training" in groups and "validation" in groups
            # 30 batches / scheduling_unit 10 = 3 training reports
            assert len([r for r in backend.records if r["group"] == "training"]) == 3
            # throughput metrics present
            rec = [r for r in backend.records if r["group"] == "training"][0]
            assert rec["metrics"]["samples_per_second"] > 0
        finally:
            mgr.__exit__(None, None, None)

    def test_final_checkpoint_written(self, tmp_path):
        ctx, mgr = make_context(tmp_path)
        try:
            Trainer(OneVarTrial(ctx)).fit()
            recs = core.LocalCheckpointRegistry(
                str(tmp_path / "checkpoints.jsonl")).list()
            assert len(recs) >= 1
            assert recs[-1]["metadata"]["steps_completed"] == 30
        finally:
            mgr.__exit__(None, None, None)

    def test_searcher_op_completed_with_metric(self, tmp_path):
        cfg_dict = {
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 20}},
            "scheduling_unit": 10,
            "checkpoint_storage": {"type": "shared_fs", "host_path": str(tmp_path)},
        }
        ctx, mgr = make_context(tmp_path, cfg_dict)
        try:
            src = core.LocalSearcherSource(ctx.config.searcher.max_length)
            ctx.core.searcher._source = src
            Trainer(OneVarTrial(ctx)).fit()
            assert len(src.completed_metrics) == 1
            assert src.completed_metrics[0] < 1.0  # loss after 20 steps
        finally:
            mgr.__exit__(None, None, None)

    def test_preemption_saves_and_exits(self, tmp_path):
        flag = tmp_path / "flag"
        flag.write_text("")  # preempt immediately
        cfg_dict = {
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 1000}},
            "scheduling_unit": 5,
            "checkpoint_storage": {"type": "shared_fs", "host_path": str(tmp_path)},
        }
        cfg = ExperimentConfig.from_dict(cfg_dict)
        with core.init(
            config=cfg, trial_id=1,
            preemption_source=core.FilePreemptionSource(str(flag)),
        ) as cctx:
            mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
            ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
            import time
            time.sleep(0.3)  # let the watcher observe the flag
            result = Trainer(OneVarTrial(ctx)).fit()
            assert result["preempted"]
            assert result["batches_trained"] < 1000
            recs = core.LocalCheckpointRegistry(
                str(tmp_path / "checkpoints.jsonl")).list()
            assert any(r["metadata"]["reason"] == "preemption" for r in recs)

    def test_restore_continues(self, tmp_path):
        # train 20, checkpoint, then resume and train to 40
        cfg_dict = {
            "searcher": {"name": "single", "metric": "loss",
                         "max_length": {"batches": 20}},
            "scheduling_unit": 10,
            "checkpoint_storage": {"type": "shared_fs", "host_path": str(tmp_path)},
        }
        ctx, mgr = make_context(tmp_path, cfg_dict)
        try:
            t = Trainer(OneVarTrial(ctx))
            t.fit()
            w_after_20 = float(np.asarray(t._final_state.params["w"]))
            recs = core.LocalCheckpointRegistry(
                str(tmp_path / "checkpoints.jsonl")).list()
            ckpt_id = recs[-1]["storage_id"]
        finally:
            mgr.__exit__(None, None, None)

        cfg_dict["searcher"]["max_length"] = {"batches": 40}
        ctx2, mgr2 = make_context(tmp_path, cfg_dict)
        try:
            t2 = Trainer(OneVarTrial(ctx2))
            result = t2.fit(latest_checkpoint=ckpt_id)
            assert result["batches_trained"] == 40
            w_final = float(np.asarray(t2._final_state.params["w"]))
            # restored from w_after_20 and kept improving toward 3.0
            assert abs(w_final - 3.0) < abs(w_after_20 - 3.0) + 1e-6
        finally:
            mgr2.__exit__(None, None, None)


class MnistMLPTrial(JaxTrial):
    def __init__(self, context):
        super().__init__(context)
        from determined_clone_tpu.models import mlp

        self.mlp = mlp
        self.cfg = mlp.MLPConfig(in_dim=784, hidden_dims=(64,), n_classes=10)
        self.x, self.y = synthetic_mnist(2048, seed=0)
        self.vx, self.vy = synthetic_mnist(512, seed=1)

    def initial_params(self, rng):
        return self.mlp.init(rng, self.cfg)

    def optimizer(self):
        return optax.adam(1e-3)

    def loss(self, params, batch, rng):
        x, y = batch
        loss = self.mlp.loss_fn(params, self.cfg, x, y)
        return loss, {}

    def eval_metrics(self, params, batch):
        from determined_clone_tpu.ops.layers import accuracy, softmax_cross_entropy

        x, y = batch
        logits = self.mlp.apply(params, self.cfg, x)
        return {
            "loss": jnp.mean(softmax_cross_entropy(logits, y)),
            "accuracy": accuracy(logits, y),
        }

    def training_data(self):
        return batch_iterator(self.x, self.y, self.global_batch_size, seed=0)

    def validation_data(self):
        return batch_iterator(self.vx, self.vy, self.global_batch_size,
                              seed=0, shuffle=False)

    @property
    def global_batch_size(self):
        return 64

    def sharding_rules(self):
        return ShardingRules()


class TestTrainerMnist:
    def test_mnist_mlp_learns_sharded(self, tmp_path):
        cfg_dict = {
            "searcher": {"name": "single", "metric": "accuracy",
                         "smaller_is_better": False,
                         "max_length": {"batches": 60}},
            "scheduling_unit": 20,
            "min_validation_period": {"batches": 20},
            "checkpoint_storage": {"type": "shared_fs", "host_path": str(tmp_path)},
        }
        mesh = make_mesh(MeshSpec(dp=4, fsdp=2))
        ctx, mgr = make_context(tmp_path, cfg_dict, mesh=mesh)
        try:
            result = Trainer(MnistMLPTrial(ctx)).fit()
            assert result["batches_trained"] == 60
            assert result["best_validation"] is not None
            assert result["best_validation"] > 0.5  # way above 0.1 chance
        finally:
            mgr.__exit__(None, None, None)
