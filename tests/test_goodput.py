"""Goodput ledger suite (telemetry/goodput.py, docs/observability.md).

Three layers, mirroring the ledger's own structure:

- unit: span→category bucketing, the compile-dedupe rule, anomaly
  overhang, explicit notes, and the conservation invariant (including a
  fabricated overcount — the only way to violate it);
- durability: the per-leg journal's kill -9 contract (line-buffered
  writes, torn-final-line tolerance, leg-number resume) and the
  restart-leg merge, where the dead time between legs must land in
  ``restart_backoff``, never as missing wall-clock;
- end-to-end: a real Trainer run must balance its books within the 1%
  tolerance (the ISSUE's enforced acceptance criterion), and a seeded
  kill -9 chaos run's merged lifetime account must attribute the
  injected restart to restart badput (@slow — the chaos lane).
"""
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from determined_clone_tpu import core, faults
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.parallel import MeshSpec, make_mesh
from determined_clone_tpu.telemetry import telemetry_from_config
from determined_clone_tpu.telemetry.goodput import (
    CATEGORIES,
    RESTART_CATEGORIES,
    GoodputLedger,
    check_conservation,
    format_goodput,
    merge_goodput,
    read_goodput,
)
from determined_clone_tpu.telemetry.metrics import MetricsRegistry
from determined_clone_tpu.training import JaxTrial, Trainer, TrialContext

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The lane contract (run_tests.sh): with the telemetry plane switched
# off, every goodput test skips instead of failing — the ledger only
# exists when telemetry does.
pytestmark = pytest.mark.skipif(
    os.environ.get("DCT_TELEMETRY_DISABLED") == "1",
    reason="telemetry plane disabled (DCT_TELEMETRY_DISABLED=1)")


@pytest.fixture(autouse=True)
def clean_fault_state(monkeypatch):
    monkeypatch.delenv("DCT_FAULT_PLAN", raising=False)
    monkeypatch.delenv("DCT_GOODPUT_DIR", raising=False)
    monkeypatch.delenv("DCT_QUEUE_WAIT_S", raising=False)
    faults.reset()
    yield
    faults.reset()


def span(name, dur_s, *, depth=0, tid=0, **args):
    return {"name": name, "ts_us": 0.0, "dur_us": dur_s * 1e6,
            "tid": tid, "tname": "consumer", "depth": depth,
            "args": args}


def instant(name, **args):
    return {"name": name, "ph": "i", "ts_us": 0.0, "dur_us": 0.0,
            "tid": 0, "tname": "consumer", "depth": 1, "args": args}


# ---------------------------------------------------------------------------
# ledger unit behaviour
# ---------------------------------------------------------------------------

def test_span_bucketing_and_conservation():
    led = GoodputLedger(trial_id=3)
    led.observe_span(span("train_dispatch", 0.5, step=1))
    led.observe_span(span("dataload_wait", 0.2))
    led.observe_span(span("host_sync", 0.1))
    led.observe_span(span("validate", 0.3))
    led.observe_span(span("checkpoint_save", 0.4))
    # nested + producer-lane + unknown spans must NOT contribute
    led.observe_span(span("eval_dispatch", 9.0, depth=1))
    led.observe_span(span("storage_upload", 9.0, depth=1))
    led.observe_span(span("produce_batch", 9.0, tid=1))  # unmapped name
    snap = led.snapshot()
    cats = snap["categories"]
    assert cats["productive"] == pytest.approx(0.5)
    assert cats["data_wait"] == pytest.approx(0.2)
    assert cats["host_sync"] == pytest.approx(0.1)
    assert cats["validation"] == pytest.approx(0.3)
    assert cats["checkpoint_save"] == pytest.approx(0.4)
    assert set(cats) == set(CATEGORIES)
    # attributed (1.5s) exceeds the microseconds of real wall-clock this
    # test took — snapshot still balances because wall is measured, and
    # the fabricated history shows up as overcount, which conservation
    # rejects: the books can't invent time
    assert snap["overcount_s"] > 0
    assert not check_conservation(snap)["ok"]


def test_unattributed_is_the_remainder_and_books_balance():
    led = GoodputLedger()
    time.sleep(0.05)
    led.observe_span(span("train_dispatch", 0.01))
    snap = led.snapshot()
    cats = snap["categories"]
    assert cats["unattributed"] > 0
    assert sum(cats.values()) == pytest.approx(snap["wall_s"], rel=1e-6)
    res = check_conservation(snap)
    assert res["ok"] and res["error_fraction"] < 0.01
    assert snap["goodput_fraction"] == pytest.approx(
        cats["productive"] / snap["wall_s"])


def test_compile_dedupe_rules():
    """The wrap_jit contract: a compiled dispatch span and its synthesized
    same-interval xla_compile record are ONE interval — the dispatch is
    re-bucketed to compile, the synthesized record ignored; only the
    explicit AOT capture counts directly."""
    led = GoodputLedger()
    led.observe_span(span("train_dispatch", 0.8, compiled=True))
    led.observe_span(span("xla_compile", 0.8))          # synthesized twin
    led.observe_span(span("xla_compile", 0.3, explicit=True))  # AOT
    cats = led.snapshot()["categories"]
    assert cats["productive"] == 0.0
    assert cats["compile"] == pytest.approx(1.1)


def test_anomaly_overhang_moves_out_of_productive():
    led = GoodputLedger()
    led.observe_span(span("train_dispatch", 0.10))
    led.observe_span(span("train_dispatch", 0.55))  # the straggler
    led.observe_span(instant("step_time_anomaly",
                             duration_s=0.55, median_s=0.10, step=2))
    cats = led.snapshot()["categories"]
    assert cats["anomaly_overhang"] == pytest.approx(0.45)
    assert cats["productive"] == pytest.approx(0.20)
    # malformed / non-positive overhang instants are ignored
    led.observe_span(instant("step_time_anomaly", duration_s=0.05,
                             median_s=0.10))
    led.observe_span(instant("step_time_anomaly", duration_s="nan?"))
    assert led.snapshot()["categories"]["anomaly_overhang"] == \
        pytest.approx(0.45)


def test_anomaly_overhang_clamps_to_available_productive():
    led = GoodputLedger()
    led.observe_span(span("train_dispatch", 0.1))
    led.observe_span(instant("step_time_anomaly",
                             duration_s=5.0, median_s=0.5))
    cats = led.snapshot()["categories"]
    # moving more than productive holds would create negative time
    assert cats["productive"] == 0.0
    assert cats["anomaly_overhang"] == pytest.approx(0.1)


def test_note_validates_category_and_pre_wall_extends_wall():
    led = GoodputLedger()
    with pytest.raises(ValueError):
        led.note("coffee_break", 1.0)
    with pytest.raises(ValueError):
        led.note("unattributed", 1.0)  # remainder is computed, not noted
    epoch_before = led.snapshot()["wall_epoch_start"]
    led.note("queue_wait", 2.5, pre_wall=True)
    snap = led.snapshot()
    # queue wait predates the ledger: it extends the accountable wall so
    # conservation still balances, and shifts the epoch anchor back so
    # the merged-leg timeline stays gap-correct
    assert snap["wall_s"] > 2.5
    assert snap["categories"]["queue_wait"] == pytest.approx(2.5)
    assert snap["wall_epoch_start"] == pytest.approx(epoch_before - 2.5,
                                                     abs=0.05)
    assert check_conservation(snap)["ok"]


def test_publish_metrics_lands_gauges():
    reg = MetricsRegistry()
    led = GoodputLedger(registry=reg, trial_id=9)
    led.observe_span(span("train_dispatch", 0.01))
    snap = led.publish_metrics()
    dump = reg.dump()
    assert "goodput_seconds_total" in dump
    assert 'category="productive"' in dump
    assert "goodput_wall_seconds" in dump
    assert "goodput_fraction" in dump
    assert snap["trial_id"] == 9


# ---------------------------------------------------------------------------
# journal durability + merge
# ---------------------------------------------------------------------------

def test_journal_write_read_roundtrip_and_meta(tmp_path):
    led = GoodputLedger(trial_id=7)
    led.attach_journal(str(tmp_path))
    led.observe_span(span("train_dispatch", 0.02))
    led.publish_metrics()
    led.observe_span(span("train_dispatch", 0.03))
    led.close()
    files = [n for n in os.listdir(tmp_path) if n.endswith(".jsonl")]
    assert files == ["goodput-trial00007-leg00001.jsonl"]
    lines = (tmp_path / files[0]).read_text().splitlines()
    meta = json.loads(lines[0])
    assert meta["kind"] == "meta" and meta["trial_id"] == 7
    assert meta["leg"] == 1
    recs = list(read_goodput(str(tmp_path)))
    assert len(recs) == 1
    # cumulative: the reader takes the LAST snapshot (close's final line)
    assert recs[0]["categories"]["productive"] == pytest.approx(0.05)
    assert recs[0]["trial_id"] == 7 and recs[0]["leg"] == 1


def test_journal_resumes_leg_numbering(tmp_path):
    for expected_leg in (1, 2, 3):
        led = GoodputLedger(trial_id=4)
        led.attach_journal(str(tmp_path))
        led.publish_metrics()
        assert led.journal.leg == expected_leg
        led.close()
    # a different trial starts its own leg sequence in the same dir
    other = GoodputLedger(trial_id=5)
    other.attach_journal(str(tmp_path))
    other.publish_metrics()
    assert other.journal.leg == 1
    other.close()
    legs = sorted((r["trial_id"], r["leg"])
                  for r in read_goodput(str(tmp_path)))
    assert legs == [(4, 1), (4, 2), (4, 3), (5, 1)]


def test_reader_tolerates_torn_final_line(tmp_path):
    led = GoodputLedger(trial_id=2)
    led.attach_journal(str(tmp_path))
    led.observe_span(span("train_dispatch", 0.04))
    led.publish_metrics()
    led.close()
    path = tmp_path / "goodput-trial00002-leg00001.jsonl"
    with open(path, "a") as f:
        f.write('{"kind": "goodput", "wall_s": 99.0, "catego')  # mid-crash
    recs = list(read_goodput(str(tmp_path)))
    assert len(recs) == 1
    assert recs[0]["wall_s"] != 99.0  # the torn line never surfaced


def test_journal_write_fault_drops_and_counts(tmp_path):
    reg = MetricsRegistry()
    led = GoodputLedger(registry=reg, trial_id=1)
    led.attach_journal(str(tmp_path))
    with faults.plan_active({"rules": [
            {"point": "goodput.write", "nth": 1, "times": 1}]}):
        led.publish_metrics()   # injected write error: dropped, not raised
        led.publish_metrics()   # plan exhausted: lands
    assert led.journal.records_dropped == 1
    assert reg.counter("goodput_records_dropped").value == 1
    assert len(list(read_goodput(str(tmp_path)))) == 1


def hand_leg(trial, leg, start, wall, **cats):
    """Write a synthetic journal leg: categories + computed remainder."""
    categories = {c: 0.0 for c in CATEGORIES}
    categories.update(cats)
    categories["unattributed"] = max(
        0.0, wall - sum(v for k, v in categories.items()
                        if k != "unattributed"))
    return {"kind": "goodput", "trial_id": trial, "leg": leg,
            "wall_s": wall, "wall_epoch_start": start,
            "wall_epoch": start + wall, "categories": categories,
            "overcount_s": 0.0,
            "goodput_fraction": categories["productive"] / wall}


def write_leg(directory, rec):
    path = os.path.join(
        directory, f"goodput-trial{rec['trial_id']:05d}"
                   f"-leg{rec['leg']:05d}.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "meta"}) + "\n")
        f.write(json.dumps(rec) + "\n")


def test_merge_attributes_inter_leg_gap_to_restart_backoff(tmp_path):
    # leg 1: 0→10s; gap of 6s (backoff + respawn); leg 2: 16→46s
    write_leg(str(tmp_path), hand_leg(7, 1, 1000.0, 10.0,
                                      productive=8.0, compile=1.0))
    write_leg(str(tmp_path), hand_leg(7, 2, 1016.0, 30.0,
                                      productive=24.0, restore_replay=3.0))
    merged = merge_goodput(str(tmp_path))
    acct = merged[7]
    assert acct["legs"] == 2
    assert acct["wall_s"] == pytest.approx(46.0)  # 10 + 6 gap + 30
    cats = acct["categories"]
    assert cats["restart_backoff"] == pytest.approx(6.0)
    assert cats["productive"] == pytest.approx(32.0)
    assert cats["restore_replay"] == pytest.approx(3.0)
    # the merged account balances too: no second went missing
    assert sum(cats.values()) == pytest.approx(acct["wall_s"])
    assert acct["goodput_fraction"] == pytest.approx(32.0 / 46.0)
    assert acct["conservation_ok"]
    text = format_goodput(merged)
    assert "trial 7" in text and "restart_backoff" in text


def test_merge_flags_violated_leg_and_ignores_clock_skew(tmp_path):
    bad = hand_leg(3, 1, 1000.0, 5.0, productive=4.0)
    bad["categories"]["productive"] = 9.0  # cook the books: overcount
    write_leg(str(tmp_path), bad)
    # leg 2 starts BEFORE leg 1 ended (clock skew): gap clamps to 0
    write_leg(str(tmp_path), hand_leg(3, 2, 1003.0, 5.0, productive=4.0))
    acct = merge_goodput(str(tmp_path))[3]
    assert not acct["conservation_ok"]
    assert acct["categories"]["restart_backoff"] == 0.0
    assert acct["wall_s"] == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# wiring: telemetry_from_config, env contracts, aggregator, master, CLI
# ---------------------------------------------------------------------------

def obs_config(**extra):
    return {"observability": {"enabled": True, **extra}}


def test_telemetry_wires_ledger_as_tracer_sink():
    tel = telemetry_from_config(obs_config())
    try:
        assert tel.goodput is not None
        with tel.tracer.span("train_dispatch", step=1):
            time.sleep(0.01)
        assert tel.goodput.snapshot()["categories"]["productive"] > 0
    finally:
        tel.close()


def test_goodput_dir_env_force_enables_and_journals(tmp_path, monkeypatch):
    monkeypatch.setenv("DCT_GOODPUT_DIR", str(tmp_path))
    tel = telemetry_from_config({})  # observability NOT enabled in config
    try:
        assert tel is not None and tel.goodput is not None
        tel.goodput.set_identity(trial_id=11)
        tel.publish(None, 4)
    finally:
        tel.close()
    recs = list(read_goodput(str(tmp_path)))
    assert [r["trial_id"] for r in recs] == [11]


def test_queue_wait_env_contract(monkeypatch):
    monkeypatch.setenv("DCT_QUEUE_WAIT_S", "1.75")
    tel = telemetry_from_config(obs_config())
    try:
        snap = tel.goodput.snapshot()
        assert snap["categories"]["queue_wait"] == pytest.approx(1.75)
        assert snap["wall_s"] > 1.75  # pre-wall time extends the account
        assert check_conservation(snap)["ok"]
    finally:
        tel.close()
    # garbage values are ignored, not fatal: telemetry must never kill
    monkeypatch.setenv("DCT_QUEUE_WAIT_S", "soon")
    tel = telemetry_from_config(obs_config())
    try:
        assert tel.goodput.snapshot()["categories"]["queue_wait"] == 0.0
    finally:
        tel.close()


def test_telemetry_disabled_env_wins(monkeypatch):
    monkeypatch.setenv("DCT_TELEMETRY_DISABLED", "1")
    monkeypatch.setenv("DCT_GOODPUT_DIR", "/tmp/nope")  # force-enable loses
    assert telemetry_from_config(obs_config()) is None


def ship_trial_snapshot(agg, trial_id, *, productive, wall,
                        experiment_id=None, **extra_cats):
    reg = MetricsRegistry()
    led = GoodputLedger(registry=reg, trial_id=trial_id)
    led.note("productive", productive)
    for cat, secs in extra_cats.items():
        led.note(cat, secs)
    snap = led.publish_metrics()
    # override the gauges' measured wall with the scenario's: the rollup
    # must reproduce whatever the trial shipped, not re-derive it
    reg.gauge("goodput_wall_seconds", "").set(wall)
    reg.gauge("goodput_fraction", "").set(productive / wall)
    agg.ingest(trial_id, [{"time": 1.0, "group": "telemetry",
                           "metrics": reg.snapshot()}],
               experiment_id=experiment_id)
    return snap


def test_aggregator_rollup_is_time_weighted():
    from determined_clone_tpu.telemetry.aggregate import (
        ClusterMetricsAggregator,
    )

    agg = ClusterMetricsAggregator()
    # busy trial: 90% goodput over 100s; idle trial: 10% over 10s
    ship_trial_snapshot(agg, 1, productive=90.0, wall=100.0,
                        experiment_id=5, checkpoint_save=5.0)
    ship_trial_snapshot(agg, 2, productive=1.0, wall=10.0, experiment_id=6)
    roll = agg.goodput_rollup()
    assert set(roll["by_trial"]) == {"1", "2"}
    assert roll["by_trial"]["1"]["experiment_id"] == 5
    assert roll["by_trial"]["1"]["categories"]["checkpoint_save"] == \
        pytest.approx(5.0)
    assert roll["wall_total_s"] == pytest.approx(110.0)
    # time-weighted: (90+1)/110, NOT the 0.5 a plain average would give
    assert roll["cluster_fraction"] == pytest.approx(91.0 / 110.0)
    summary = agg.summary()
    assert summary["goodput"]["cluster_fraction"] == \
        pytest.approx(91.0 / 110.0)
    dump = agg.dump()
    assert 'dct_goodput_fraction{trial_id="1"}' in dump
    assert "dct_goodput_cluster_fraction" in dump


def test_master_goodput_route_and_cli(tmp_path, capsys):
    from determined_clone_tpu.api.inprocess import InProcessMaster
    from determined_clone_tpu.cli.cli import main as cli_main

    master = InProcessMaster()
    master.register_trial(1, 5)
    ship_trial_snapshot(master.aggregator, 1, productive=8.0, wall=10.0,
                        experiment_id=5)
    status, roll, ctype = master.handle("GET", "/api/v1/cluster/goodput")
    assert status == 200 and ctype == "application/json"
    assert roll["by_trial"]["1"]["goodput_fraction"] == pytest.approx(0.8)

    # offline CLI path: merge a journal directory (sleep past the span's
    # fabricated duration so the leg's books genuinely balance)
    led = GoodputLedger(trial_id=1)
    led.attach_journal(str(tmp_path))
    time.sleep(0.03)
    led.observe_span(span("train_dispatch", 0.02))
    led.close()
    assert cli_main(["goodput", "--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "trial 1" in out and "productive" in out
    assert cli_main(["goodput", "--dir", str(tmp_path), "--json"]) == 0
    accounts = json.loads(capsys.readouterr().out)
    assert accounts["1"]["conservation_ok"] is True
    # empty directory: exit 1, not a stack trace
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli_main(["goodput", "--dir", str(empty)]) == 1


# ---------------------------------------------------------------------------
# end-to-end: a real trainer run balances its books (tier-1 acceptance)
# ---------------------------------------------------------------------------

class DriftTrial(JaxTrial):
    """Same shape as the fault-tolerance suite's drift trial: loss depends
    on batch content so replay mistakes would change the final params."""

    n_batches = 24

    def initial_params(self, rng):
        return {"w": jnp.zeros(())}

    def optimizer(self):
        return optax.sgd(0.05)

    def loss(self, params, batch, rng):
        target = jnp.mean(batch)
        loss = (params["w"] - target) ** 2
        return loss, {"w": params["w"]}

    def training_data(self):
        for i in range(self.n_batches):
            yield np.full((4, 1), float(i % 7), np.float32)

    def validation_data(self):
        return [np.ones((4, 1), np.float32)]

    @property
    def global_batch_size(self):
        return 4


def drift_config(storage, batches=24):
    return {
        "searcher": {"name": "single", "metric": "loss",
                     "max_length": {"batches": batches}},
        "scheduling_unit": 4,
        "min_checkpoint_period": {"batches": 8},
        "checkpoint_storage": {"type": "shared_fs",
                               "host_path": str(storage)},
        "optimizations": {"prefetch_depth": 0},
        "observability": {"enabled": True},
    }


def run_trial(storage, *, latest=None, trial_id=1):
    """One trainer leg with goodput accounting; returns the final ledger
    snapshot taken inside the core context (close() writes the journal's
    last line after this)."""
    cfg = ExperimentConfig.from_dict(drift_config(storage))
    mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
    with core.init(config=cfg, trial_id=trial_id) as cctx:
        ctx = TrialContext(config=cfg, hparams={}, core=cctx, mesh=mesh)
        result = Trainer(DriftTrial(ctx)).fit(latest_checkpoint=latest)
        snap = cctx.telemetry.goodput.snapshot()
    return result, snap


def test_real_trainer_run_conserves_wall_clock(tmp_path):
    """The ISSUE's enforced acceptance criterion: on a real run the
    categories sum to wall-clock within 1%, goodput_fraction is non-null,
    and the external stopwatch agrees with the ledger's wall."""
    t0 = time.perf_counter()
    result, snap = run_trial(tmp_path)
    external_wall = time.perf_counter() - t0
    assert result["batches_trained"] == 24
    res = check_conservation(snap)
    assert res["ok"], res
    assert snap["overcount_s"] == 0.0
    assert snap["goodput_fraction"] is not None
    assert snap["goodput_fraction"] > 0
    # the ledger is born inside core.init, so its wall is a subset of the
    # external measurement — it must never exceed it
    assert snap["wall_s"] <= external_wall + 0.01
    cats = snap["categories"]
    assert cats["productive"] > 0
    assert cats["checkpoint_save"] > 0      # batches 8/16/24 committed
    assert cats["restart_backoff"] == 0.0   # uninterrupted
    assert cats["restore_replay"] == 0.0


# ---------------------------------------------------------------------------
# chaos: kill -9, restart, merge — injected death is restart badput
# ---------------------------------------------------------------------------

GOODPUT_CHAOS_RUNNER = '''
import json, os, sys
sys.path.insert(0, {repo!r})
from determined_clone_tpu.utils.host_steering import steer_to_host_cpu
steer_to_host_cpu(8)
import jax
sys.path.insert(0, {testdir!r})
from test_goodput import DriftTrial, drift_config
from determined_clone_tpu import core
from determined_clone_tpu.config import ExperimentConfig
from determined_clone_tpu.parallel import MeshSpec, make_mesh
from determined_clone_tpu.training import Trainer, TrialContext

latest = os.environ.get("DCT_RESUME_FROM") or None
cfg = ExperimentConfig.from_dict(drift_config({storage!r}, batches=24))
mesh = make_mesh(MeshSpec(dp=1), jax.devices()[:1])
with core.init(config=cfg, trial_id=1) as cctx:
    ctx = TrialContext(config=cfg, hparams={{}}, core=cctx, mesh=mesh)
    result = Trainer(DriftTrial(ctx)).fit(latest_checkpoint=latest)
print("COMPLETED", result["batches_trained"])
'''


def chaos_env(goodput_dir, **extra):
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PALLAS_AXON_POOL_IPS": "",
        "DCT_GOODPUT_DIR": str(goodput_dir),
        **extra,
    }


@pytest.mark.slow
def test_kill9_restart_legs_merge_into_restart_badput(tmp_path):
    """The full durability story: leg 1 is hard-killed on step 13 (after
    the batch-8 journal line is already on disk, line-buffered), leg 2
    resumes from the batch-8 checkpoint and completes. merge_goodput must
    fold both legs plus the dead time between them into one account whose
    books balance — the injected restart shows up as restart badput
    (restart_backoff gap + restore_replay), never as missing time — and
    whose totals match an uninterrupted baseline up to the measured
    restart overhead."""
    storage = tmp_path / "ckpts"
    storage.mkdir()
    goodput_dir = tmp_path / "goodput"
    script = tmp_path / "chaos_run.py"
    script.write_text(GOODPUT_CHAOS_RUNNER.format(
        repo=REPO, testdir=os.path.join(REPO, "tests"),
        storage=str(storage)))

    # leg 1: die on the 13th step dispatch — after the batch-8 commit and
    # its chunk-boundary journal writes, kill -9 semantics (os._exit)
    env = chaos_env(goodput_dir, DCT_FAULT_PLAN=json.dumps({"rules": [
        {"point": "training.pre_step", "action": "exit",
         "nth": 13, "exit_code": 137}]}))
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 137, proc.stdout + proc.stderr
    legs = list(read_goodput(str(goodput_dir)))
    assert len(legs) == 1  # the dead leg's journal survived the kill
    assert legs[0]["leg"] == 1
    assert check_conservation(legs[0])["ok"]

    # leg 2: resume from the committed batch-8 checkpoint, run to the end
    reg = core.LocalCheckpointRegistry(str(storage / "checkpoints.jsonl"))
    recs = reg.list()
    assert len(recs) == 1
    assert recs[0]["metadata"]["steps_completed"] == 8
    env = chaos_env(goodput_dir, DCT_RESUME_FROM=recs[0]["storage_id"])
    proc = subprocess.run([sys.executable, str(script)], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "COMPLETED 24" in proc.stdout

    # uninterrupted baseline: same script rendered with its own storage
    # and journal dir, so the two runs differ only in the injected fault
    baseline_storage = tmp_path / "baseline-ckpts"
    baseline_storage.mkdir()
    baseline_goodput = tmp_path / "baseline-goodput"
    baseline_script = tmp_path / "baseline_run.py"
    baseline_script.write_text(GOODPUT_CHAOS_RUNNER.format(
        repo=REPO, testdir=os.path.join(REPO, "tests"),
        storage=str(baseline_storage)))
    env = chaos_env(baseline_goodput)
    proc = subprocess.run([sys.executable, str(baseline_script)], env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "COMPLETED 24" in proc.stdout

    merged = merge_goodput(str(goodput_dir))
    assert list(merged) == [1]
    acct = merged[1]
    assert acct["legs"] == 2
    assert acct["conservation_ok"], acct
    cats = acct["categories"]
    # every leg's books balance AND the merged ones do: nothing missing
    assert sum(cats.values()) == pytest.approx(acct["wall_s"], rel=0.01)
    # the injected death is restart badput...
    restart_badput = sum(cats[c] for c in RESTART_CATEGORIES)
    assert restart_badput > 0, cats
    assert cats["restart_backoff"] > 0  # the inter-leg dead time

    baseline = merge_goodput(str(baseline_goodput))[1]
    assert baseline["legs"] == 1
    assert baseline["conservation_ok"]
    base_cats = baseline["categories"]
    base_restart = sum(base_cats[c] for c in RESTART_CATEGORIES)
    assert base_restart == pytest.approx(0.0, abs=0.01)
    # ...and NOT unattributed: the chaos run may carry up to one extra
    # process startup of unattributed glue versus the baseline (two legs,
    # two startups), but the restart gap itself must not leak into it
    overhead = acct["wall_s"] - baseline["wall_s"]
    assert cats["unattributed"] <= (
        2.0 * base_cats["unattributed"] + 0.25 * max(overhead, 0.0) + 2.0)
    # merged productive ≈ baseline productive + the replayed batches'
    # re-training (legs trained 12 + 16 batches vs 24): generous bound
    assert cats["productive"] <= base_cats["productive"] * 2.0 + 2.0
    assert cats["productive"] >= base_cats["productive"] * 0.3
