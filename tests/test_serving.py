"""Continuous-batching serving surface (docs/serving.md): paged-KV
parity against the uncached forward, compile discipline under the bucket
budget, admission control/backpressure, checkpoint hot-load, the static
run-to-completion baseline, the HTTP front-end, and the KV-cached decode
FLOPs accounting that makes serving MFU honest."""
import json
import urllib.error
import urllib.request
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import pytest

from determined_clone_tpu.core._serialization import save_pytree
from determined_clone_tpu.models import gpt
from determined_clone_tpu.serving import (
    BlockAllocator,
    BucketSpec,
    InferenceEngine,
    KVCacheConfig,
    ServerOverloaded,
    bucket_for,
    pow2_buckets,
)
from determined_clone_tpu.serving.http import (
    ServingHTTPServer,
    generate_over_http,
)
from determined_clone_tpu.storage import (
    CASStorageManager,
    SharedFSStorageManager,
)
from determined_clone_tpu.telemetry import flops as flops_mod
from determined_clone_tpu.utils.retry import RetryPolicy

CFG = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32, n_heads=4,
                    d_ff=64, max_seq_len=48, remat=False,
                    attention_impl="mha")

BUCKETS = BucketSpec.build(4, 16)
CACHE = KVCacheConfig(num_blocks=16, block_size=8)

# mixed lengths on purpose: the parity + compile-discipline tests must
# exercise several (batch, prompt-length) shapes
PROMPTS = [[5, 17, 3, 88, 41], [9] * 11, [1, 2, 3]]


@pytest.fixture(scope="module")
def params():
    return gpt.init(jax.random.PRNGKey(0), CFG)


def naive_greedy(params, prompt, max_new):
    """Reference decode: full-context uncached forward every step."""
    toks = list(prompt)
    for _ in range(max_new):
        logits = gpt.apply(params, CFG, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def make_engine(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("cache", CACHE)
    return InferenceEngine(params, CFG, **kw)


# -- bucketing / allocator units --------------------------------------------

def test_pow2_buckets():
    assert pow2_buckets(1, 8) == (1, 2, 4, 8)
    assert pow2_buckets(4, 100) == (4, 8, 16, 32, 64, 128)
    assert bucket_for(5, (4, 8, 16)) == 8
    assert bucket_for(8, (4, 8, 16)) == 8
    with pytest.raises(ValueError):
        bucket_for(17, (4, 8, 16))
    with pytest.raises(ValueError):
        pow2_buckets(0, 4)


def test_bucket_spec_validation_and_budget():
    spec = BucketSpec(batch_buckets=(1, 2, 4), prefill_len_buckets=(8, 16))
    assert spec.max_batch == 4
    assert spec.max_prefill_len == 16
    assert spec.program_budget == 3 * 2 + 3
    with pytest.raises(ValueError):
        BucketSpec(batch_buckets=(3,), prefill_len_buckets=(8,))
    with pytest.raises(ValueError):
        BucketSpec(batch_buckets=(4, 2), prefill_len_buckets=(8,))
    with pytest.raises(ValueError):
        BucketSpec(batch_buckets=(), prefill_len_buckets=(8,))


def test_block_allocator():
    alloc = BlockAllocator(KVCacheConfig(num_blocks=4, block_size=8))
    assert alloc.free_blocks() == 4
    a = alloc.allocate(17)  # 3 blocks
    assert len(a) == 3 and alloc.free_blocks() == 1
    assert alloc.can_allocate(8) and not alloc.can_allocate(9)
    with pytest.raises(MemoryError):
        alloc.allocate(16)
    alloc.release(a)
    assert alloc.free_blocks() == 4
    with pytest.raises(ValueError):
        alloc.release(a[:1])  # double free
    with pytest.raises(ValueError):
        alloc.release([99])  # bogus id


# -- the tier-1 contract: parity + compile discipline ------------------------

def test_paged_decode_token_identical_and_compile_budget(params):
    """Mixed-length requests through the continuous scheduler produce
    EXACTLY the tokens of the naive uncached forward (greedy), and the
    shared jitted forward never compiles more programs than the bucket
    budget — the two acceptance properties of the serving tentpole."""
    expected = {i: naive_greedy(params, p, 12)
                for i, p in enumerate(PROMPTS)}
    with make_engine(params) as eng:
        handles = [eng.submit(p, 12, request_id=str(i))
                   for i, p in enumerate(PROMPTS)]
        results = {int(h.result(timeout=120.0).request_id):
                   h.result(timeout=120.0) for h in handles}
        # a second wave at different batch sizes exercises more shapes
        again = [eng.submit(p, 5) for p in PROMPTS[:2]]
        for h in again:
            h.result(timeout=120.0)
        compiled = eng.programs_compiled()
        budget = eng.buckets.program_budget
        stats = eng.stats()
    for i in range(len(PROMPTS)):
        assert results[i].tokens == expected[i], f"request {i} diverged"
        assert results[i].finish_reason == "length"
        assert results[i].prompt_len == len(PROMPTS[i])
    assert 0 < compiled <= budget, (compiled, budget)
    assert stats.completed == 5
    assert stats.tokens_generated == 3 * 12 + 2 * 5
    assert stats.free_blocks == CACHE.num_blocks  # everything released


def test_warmup_precompiles_full_ladder(params):
    """warmup() compiles EXACTLY the program budget up front, leaves the
    KV pools untouched (dummy calls are fully masked), and no later
    traffic — including the one-request-at-a-time arrival pattern that
    hits the small batch buckets a burst never exercises — adds a
    single program. The mid-traffic compile stall this prevents is what
    collapsed the bench's top load point ~10x before warmup existed."""
    expected = naive_greedy(params, PROMPTS[0], 8)
    with make_engine(params) as eng:
        compiled = eng.warmup()
        assert compiled == eng.buckets.program_budget
        # trickle: each request admitted alone → batch-bucket-1 prefill,
        # the shape a warm burst at full batch never compiles
        for _ in range(2):
            r = eng.generate(PROMPTS[0], 8)
            assert r.tokens == expected  # pools uncorrupted by warmup
        # then a burst at full batch for the other buckets
        hs = [eng.submit(p, 4) for p in PROMPTS]
        for h in hs:
            h.result(timeout=120.0)
        assert eng.programs_compiled() == compiled  # nothing new to compile
    with make_engine(params) as eng:
        # white-box: an un-notified queue entry keeps the scheduler
        # parked, so the busy engine is observed deterministically
        eng._queue.append(object())
        with pytest.raises(RuntimeError, match="idle"):
            eng.warmup()
        eng._queue.clear()
        eng.close()
        with pytest.raises(RuntimeError, match="closed"):
            eng.warmup()


def test_eos_stops_early(params):
    ref = naive_greedy(params, PROMPTS[0], 12)
    eos = ref[3]
    # the engine stops at the FIRST occurrence of eos (an untrained model
    # may emit it earlier than position 3 — don't assume distinct tokens)
    stop = ref.index(eos) + 1
    with make_engine(params) as eng:
        r = eng.generate(PROMPTS[0], 12, eos_token_id=eos)
    assert r.finish_reason == "eos"
    assert r.tokens == ref[:stop]


def test_static_baseline_matches_and_shares_programs(params):
    """run_static (run-to-completion groups) must emit the same tokens —
    same params, same greedy rule, same jitted programs — so the bench
    comparison isolates scheduling policy alone."""
    expected = [naive_greedy(params, p, n)
                for p, n in zip(PROMPTS, (4, 9, 2))]
    with make_engine(params) as eng:
        out = eng.run_static(list(zip(PROMPTS, (4, 9, 2))), timeout=120.0)
        compiled = eng.programs_compiled()
    assert [r.tokens for r in out] == expected
    assert 0 < compiled <= BUCKETS.program_budget


def test_telemetry_spans_and_metrics(params):
    with make_engine(params) as eng:
        eng.generate(PROMPTS[0], 4)
        dump = eng.registry.dump()
    for name in ("serving_queue_wait_seconds", "serving_prefill_seconds",
                 "serving_decode_step_seconds",
                 "serving_request_total_seconds",
                 "serving_requests_completed_total",
                 "serving_tokens_generated_total"):
        assert name in dump, name


# -- admission control / backpressure ----------------------------------------

def test_admission_rejects_and_backoff(params):
    fast = RetryPolicy(name="t", max_attempts=2, base_delay_s=0.01,
                       multiplier=1.0, max_delay_s=0.01,
                       retryable=(ServerOverloaded,))
    with make_engine(params, max_queue_depth=0) as eng:
        with pytest.raises(ServerOverloaded):
            eng.submit(PROMPTS[0], 2)
        with pytest.raises(ServerOverloaded):
            eng.submit_with_backoff(PROMPTS[0], 2, policy=fast)
        assert eng.stats().rejected >= 3  # 1 direct + 2 backoff attempts


def test_never_servable_requests_rejected_upfront(params):
    with make_engine(params) as eng:
        with pytest.raises(ValueError):
            eng.submit([], 4)  # empty prompt
        with pytest.raises(ValueError):
            eng.submit(list(range(17)), 4)  # > largest prefill bucket
        with pytest.raises(ValueError):
            eng.submit([1, 2], CFG.max_seq_len)  # total > max_seq_len
        with pytest.raises(ValueError):
            eng.submit([1, 2], 0)  # no tokens requested


def test_closed_engine_refuses(params):
    eng = make_engine(params)
    eng.close()
    with pytest.raises(RuntimeError):
        eng.submit(PROMPTS[0], 2)


# -- checkpoint hot-load ------------------------------------------------------

def test_hot_load_from_cas_swaps_params(params, tmp_path):
    """Serve under params A, hot-load params B from a CAS-backed store,
    and the very next generation must match the naive forward under B —
    no restart, no re-jit (program count stays bounded)."""
    params_b = gpt.init(jax.random.PRNGKey(7), CFG)
    store = CASStorageManager(
        SharedFSStorageManager(str(tmp_path / "store")))
    with store.store_path("ck-b", str(tmp_path)) as d:
        save_pytree(d, params_b)
    store.commit("ck-b")

    ref_a = naive_greedy(params, PROMPTS[0], 6)
    with make_engine(params) as eng:
        assert eng.generate(PROMPTS[0], 6).tokens == ref_a
        dt = eng.hot_load(store, "ck-b", base_tmp=str(tmp_path))
        assert dt >= 0.0
        got = eng.generate(PROMPTS[0], 6).tokens
        compiled = eng.programs_compiled()
        # the swap installed the restored tree (greedy token streams of
        # two untrained models can coincide — check the params, not the
        # sampled tokens, to prove the swap happened)
        swapped = jax.tree.leaves(eng._params)
    ref_b = naive_greedy(params_b, PROMPTS[0], 6)
    assert got == ref_b
    leaves_a = jax.tree.leaves(params)
    leaves_b = jax.tree.leaves(params_b)
    assert any(not jnp.array_equal(a, b)
               for a, b in zip(leaves_a, leaves_b))
    assert all(jnp.array_equal(s, b) for s, b in zip(swapped, leaves_b))
    assert compiled <= BUCKETS.program_budget


# -- HTTP surface -------------------------------------------------------------

def test_http_generate_healthz_metrics(params):
    ref = naive_greedy(params, PROMPTS[2], 5)
    with make_engine(params) as eng, ServingHTTPServer(eng) as srv:
        out = generate_over_http(srv.url, PROMPTS[2], max_new_tokens=5)
        assert out["tokens"] == ref
        assert out["finish_reason"] == "length"
        assert out["latency"]["total_s"] >= 0

        with urllib.request.urlopen(f"{srv.url}/healthz",
                                    timeout=30) as resp:
            health = json.loads(resp.read())
        assert health["ok"] and health["stats"]["completed"] >= 1

        with urllib.request.urlopen(f"{srv.url}/metrics",
                                    timeout=30) as resp:
            metrics = resp.read().decode()
        assert "serving_requests_completed_total" in metrics


def test_http_error_codes(params):
    with make_engine(params) as eng, ServingHTTPServer(eng) as srv:
        bad = urllib.request.Request(
            f"{srv.url}/v1/generate", data=b'{"prompt": "nope"}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(bad, timeout=30)
        assert exc.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{srv.url}/nope", timeout=30)
        assert exc.value.code == 404


# -- KV-cached decode FLOPs (telemetry/flops.py) ------------------------------

@dataclass
class _TinyCfg:
    d_model: int = 4
    d_ff: int = 8
    n_layers: int = 2
    vocab_size: int = 16


def test_decode_flops_hand_computed():
    """d=4, f=8, L=2, V=16 at context 10, worked by hand:
    attention = L·(8d² + 4cd) = 2·(128 + 160) = 576
    mlp       = L·4df         = 2·128        = 256
    embedding = 2dV           =                128
    """
    out = flops_mod.gpt_decode_flops_per_token(_TinyCfg(), 10)
    assert out["attention"] == 576.0
    assert out["mlp"] == 256.0
    assert out["embedding"] == 128.0
    assert out["total"] == 960.0


def test_prefill_flops_hand_computed():
    """P=4 prompt: per-token at s=4 is 2·(128+64) + 256 + 128 = 768,
    times 4 tokens = 3072."""
    out = flops_mod.gpt_prefill_flops(_TinyCfg(), 4)
    assert out["total"] == 3072.0
    assert out["attention"] == 4 * 2 * (128 + 64)


def test_generation_flops_is_prefill_plus_decode_tail():
    """prefill(4) + decode@ctx5 + decode@ctx6: the first generated token
    falls out of the prefill logits, so n=3 pays only 2 decode steps."""
    cfg = _TinyCfg()
    total = flops_mod.gpt_generation_flops(cfg, 4, 3)
    expect = (flops_mod.gpt_prefill_flops(cfg, 4)["total"]
              + flops_mod.gpt_decode_flops_per_token(cfg, 5)["total"]
              + flops_mod.gpt_decode_flops_per_token(cfg, 6)["total"])
    assert total == expect == 3072.0 + 800.0 + 832.0


def test_decode_flops_linear_in_context_not_quadratic():
    """The whole point of the split: decode cost grows linearly with
    context while prefill per-token cost grows with prompt length."""
    cfg = _TinyCfg()
    d1 = flops_mod.gpt_decode_flops_per_token(cfg, 100)["total"]
    d2 = flops_mod.gpt_decode_flops_per_token(cfg, 200)["total"]
    d3 = flops_mod.gpt_decode_flops_per_token(cfg, 300)["total"]
    assert d3 - d2 == d2 - d1  # constant marginal cost per context token
