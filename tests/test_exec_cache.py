"""Persistent AOT executable cache (docs/checkpoint_storage.md,
"Executable cache"): ExecKey invalidation, store/load roundtrips on the
CAS blob service, torn-blob and fault-injection degradation, GC safety
of the ``cas/exec/`` namespace, the per-namespace storage stats split,
and the warm-start contract — a second process (or a cleared-cache
second engine) loads every ladder program instead of compiling, with
bit-identical greedy output."""
import dataclasses
import glob
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

import determined_clone_tpu
from determined_clone_tpu import faults
from determined_clone_tpu.storage import (
    CASStorageManager,
    ExecutableCache,
    SharedFSStorageManager,
    TransferPool,
)
from determined_clone_tpu.storage import exec_cache as exec_mod
from determined_clone_tpu.storage.cas import (
    EXEC_BLOB_PREFIX,
    EXEC_INDEX_PREFIX,
)
from determined_clone_tpu.telemetry import MetricsRegistry
from determined_clone_tpu.telemetry.xla import AotDispatcher, aot_compile

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.abspath(determined_clone_tpu.__file__)))


@pytest.fixture(autouse=True)
def clean_state(monkeypatch):
    """No fault plan, no ambient default cache, no env leakage."""
    monkeypatch.delenv("DCT_FAULT_PLAN", raising=False)
    monkeypatch.delenv(exec_mod.ENV_DIR, raising=False)
    faults.reset()
    exec_mod.set_default_cache(None)
    yield
    faults.reset()
    exec_mod.set_default_cache(None)


def make_cache(tmp_path, name="exec-store"):
    return ExecutableCache(SharedFSStorageManager(str(tmp_path / name)))


def compile_one(scale=2.0):
    """A fresh jitted program + compiled executable + example arg."""
    jitted = jax.jit(lambda x: x * scale + 1.0)
    x = jnp.arange(8.0)
    compiled = jitted.lower(x).compile()
    return jitted, compiled, x


# ---------------------------------------------------------------------------
# keying / invalidation
# ---------------------------------------------------------------------------

def test_exec_key_digest_is_canonical_and_field_sensitive(tmp_path):
    cache = make_cache(tmp_path)
    k1 = cache.key_for("ab" * 32)
    assert k1 == cache.key_for("ab" * 32)
    assert k1.digest() == cache.key_for("ab" * 32).digest()
    # every field participates: jaxlib skew, platform skew, mesh skew,
    # and program changes each produce a different digest
    for field, value in [("fingerprint", "cd" * 32),
                         ("mesh", "mesh(data=8)"),
                         ("jaxlib", "jax-9.9/jaxlib-9.9"),
                         ("platform", "tpu")]:
        assert dataclasses.replace(k1, **{field: value}).digest() \
            != k1.digest()


def test_mesh_fingerprint_forms():
    assert exec_mod.mesh_fingerprint(None) == "none"
    assert exec_mod.mesh_fingerprint({"model": 2, "data": 4}) == \
        "mesh(data=4,model=2)"
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))
    fp = exec_mod.mesh_fingerprint(mesh)
    assert fp.startswith("mesh(data=1)")


def test_stale_key_misses_never_serves_wrong_executable(tmp_path):
    cache = make_cache(tmp_path)
    _, compiled, x = compile_one()
    key = cache.key_for("ab" * 32)
    assert cache.store(key, compiled, program="p")
    # a second runtime with a different jaxlib/platform computes a
    # different digest — there is no entry to find, hence a miss (the
    # wrong executable is unreachable by construction)
    stale = dataclasses.replace(key, jaxlib="jax-0.0/jaxlib-0.0")
    assert cache.load(stale) is None
    assert cache.session["misses"] == 1
    # the real key still loads
    assert cache.load(key) is not None


def test_index_blob_key_cross_check(tmp_path):
    # an index entry pointing at a blob serialized under a DIFFERENT key
    # must refuse to load (never deserialize a foreign executable)
    cache = make_cache(tmp_path)
    _, compiled, x = compile_one()
    k1 = cache.key_for("ab" * 32)
    k2 = cache.key_for("cd" * 32)
    assert cache.store(k1, compiled, program="p")
    assert cache.store(k2, compiled, program="p")
    store_root = str(tmp_path / "exec-store" / "cas")
    idx1 = os.path.join(store_root, cache._index_rel(k1.digest()))
    idx2 = os.path.join(store_root, cache._index_rel(k2.digest()))
    with open(idx1) as f:
        entry1 = json.load(f)
    with open(idx2) as f:
        entry2 = json.load(f)
    entry1["blob"] = entry2["blob"]  # k1's index now points at k2's blob
    with open(idx1, "w") as f:
        json.dump(entry1, f)
    fresh = make_cache(tmp_path)
    assert fresh.load(k1) is None
    assert fresh.session["errors"] == 1
    assert fresh.load(k2) is not None


# ---------------------------------------------------------------------------
# roundtrip / degradation
# ---------------------------------------------------------------------------

def test_store_load_roundtrip_executes_identically(tmp_path):
    cache = make_cache(tmp_path)
    registry = MetricsRegistry()
    jitted, compiled, x = compile_one()
    key = cache.key_for("ab" * 32)
    assert cache.store(key, compiled, program="roundtrip",
                       compile_seconds=1.25, registry=registry)

    fresh = make_cache(tmp_path)  # same backend, empty session
    loaded = fresh.load(key, registry=registry)
    assert loaded is not None
    compiled2, meta = loaded
    assert meta["program"] == "roundtrip"
    assert meta["compile_seconds"] == 1.25
    assert meta["load_seconds"] > 0
    assert jnp.array_equal(compiled2(x), jitted(x))
    assert fresh.session == dict(fresh.session, hits=1, misses=0)
    snap = registry.snapshot()
    assert snap["xla_exec_cache_hits_total"]["value"] == 1.0
    assert snap["xla_exec_cache_load_seconds"]["count"] == 1


def test_missing_entry_is_a_plain_miss(tmp_path):
    cache = make_cache(tmp_path)
    registry = MetricsRegistry()
    assert cache.load(cache.key_for("ab" * 32), registry=registry) is None
    assert cache.session["misses"] == 1
    assert cache.session["errors"] == 0  # absence is not an error
    assert registry.snapshot()[
        "xla_exec_cache_misses_total"]["value"] == 1.0


def test_torn_blob_degrades_to_miss(tmp_path):
    cache = make_cache(tmp_path)
    _, compiled, x = compile_one()
    key = cache.key_for("ab" * 32)
    assert cache.store(key, compiled, program="p")
    [blob_path] = glob.glob(str(
        tmp_path / "exec-store" / "cas" / EXEC_BLOB_PREFIX / "*" / "*"))
    size = os.path.getsize(blob_path)
    with open(blob_path, "r+b") as f:
        f.truncate(size // 2)
    fresh = make_cache(tmp_path)  # no local cache: must hit the torn blob
    assert fresh.load(key) is None
    assert fresh.session["errors"] == 1
    assert fresh.session["misses"] == 1


def test_fault_points_cover_both_directions(tmp_path):
    cache = make_cache(tmp_path)
    _, compiled, x = compile_one()
    key = cache.key_for("ab" * 32)
    plan = faults.activate(faults.plan_from_dict({"rules": [
        {"point": "exec_cache.store", "exc": "io"}]}))
    assert cache.store(key, compiled, program="p") is False
    assert cache.session["errors"] == 1
    faults.deactivate(plan)
    assert cache.store(key, compiled, program="p") is True

    plan = faults.activate(faults.plan_from_dict({"rules": [
        {"point": "exec_cache.load", "exc": "io"}]}))
    assert cache.load(key) is None  # injected: degrades to a miss
    assert cache.session["misses"] == 1
    faults.deactivate(plan)
    assert cache.load(key) is not None


# ---------------------------------------------------------------------------
# compile-path integration (aot_compile / AotDispatcher)
# ---------------------------------------------------------------------------

def test_aot_compile_is_cache_first(tmp_path):
    cache = make_cache(tmp_path)
    registry = MetricsRegistry()
    x = jnp.arange(8.0)

    fn1 = jax.jit(lambda v: v * 3.0 - 1.0)
    call1, rec1 = aot_compile(fn1, (x,), program="p", registry=registry,
                              exec_cache=cache)
    assert rec1 is not None and not rec1.cache_hit
    out1 = call1(x)

    jax.clear_caches()  # nothing in-memory survives into "process 2"
    fn2 = jax.jit(lambda v: v * 3.0 - 1.0)
    call2, rec2 = aot_compile(fn2, (x,), program="p", registry=registry,
                              exec_cache=cache)
    assert rec2 is not None and rec2.cache_hit
    assert rec2.compile_time_saved_s and rec2.compile_time_saved_s > 0
    assert rec2.cache_load_seconds and rec2.cache_load_seconds > 0
    assert jnp.array_equal(call2(x), out1)
    snap = registry.snapshot()
    assert snap["xla_exec_cache_hits_total"]["value"] == 1.0
    assert snap["xla_exec_cache_misses_total"]["value"] == 1.0
    assert snap["xla_exec_cache_saved_seconds_total"]["value"] > 0


def test_aot_compile_with_statics_prunes_for_the_executable(tmp_path):
    # jit statics are burned into the program: the AOT wrapper must call
    # the executable with dynamic args only, NOT fall back to the jit
    # cache (the fallback would silently re-compile every program and the
    # warm-start contract would be a lie)
    cache = make_cache(tmp_path)
    x = jnp.arange(8.0)
    fn = jax.jit(lambda v, flavor: v + len(flavor), static_argnums=(1,))
    call, rec = aot_compile(fn, (x, "abc"), program="p", exec_cache=cache)
    assert rec is not None
    out = call(x, "abc")
    assert jnp.array_equal(out, x + 3)
    assert fn._cache_size() == 0  # the executable ran, not the jit cache


def test_dispatcher_warm_then_dispatch_without_jit(tmp_path):
    cache = make_cache(tmp_path)
    fn = jax.jit(lambda v: v * 2.0)
    disp = AotDispatcher(fn, program="p", exec_cache=cache)
    x = jnp.arange(8.0)
    disp.warm(x)
    assert disp._cache_size() == 1
    assert disp.fallback_compiles() == 0
    out = disp(x)  # same signature: served by the resident executable
    assert jnp.array_equal(out, x * 2.0)
    assert disp.fallback_compiles() == 0
    # an unwarmed signature falls back to the jit cache (counted)
    y = jnp.arange(4.0)
    assert jnp.array_equal(disp(y), y * 2.0)
    assert disp.fallback_compiles() == 1
    summary = disp.cache_summary()
    assert summary["programs"] == 1
    assert summary["exec_cache_misses"] == 1
    assert summary["fallback_compiles"] == 1


def test_default_cache_resolution(tmp_path, monkeypatch):
    assert exec_mod.default_cache() is None
    monkeypatch.setenv(exec_mod.ENV_DIR, str(tmp_path / "ambient"))
    c1 = exec_mod.default_cache()
    assert c1 is not None
    assert exec_mod.default_cache() is c1  # memoized per path
    explicit = make_cache(tmp_path)
    exec_mod.set_default_cache(explicit)  # explicit beats environment
    assert exec_mod.default_cache() is explicit
    exec_mod.set_default_cache(None)  # clearing re-enables env resolution
    assert exec_mod.default_cache() is not None
    monkeypatch.delenv(exec_mod.ENV_DIR)
    assert exec_mod.default_cache() is None


# ---------------------------------------------------------------------------
# GC safety + stats split
# ---------------------------------------------------------------------------

def make_cas(tmp_path):
    inner = SharedFSStorageManager(str(tmp_path / "store"))
    mgr = CASStorageManager(inner, chunk_size=1024,
                            pool=TransferPool(workers=0))
    return mgr, inner


def write_payload(src, blob):
    os.makedirs(src, exist_ok=True)
    with open(os.path.join(src, "weights.bin"), "wb") as f:
        f.write(blob)


def exec_rels(inner):
    return {r for r in inner.list_files("cas")
            if r.startswith((EXEC_BLOB_PREFIX + "/",
                             EXEC_INDEX_PREFIX + "/"))}


def test_chunk_gc_never_sweeps_exec_entries(tmp_path):
    mgr, inner = make_cas(tmp_path)
    _, compiled, x = compile_one()
    ec = mgr.exec_cache()
    assert ec.store(ec.key_for("ab" * 32), compiled, program="p")
    before = exec_rels(inner)
    assert len(before) == 2  # one blob + one index entry

    src = str(tmp_path / "src")
    write_payload(src, os.urandom(3 * 1024))
    mgr.upload(src, "ck-1")
    write_payload(src, os.urandom(3 * 1024))
    mgr.upload(src, "ck-2")
    # ref-count GC runs on every delete; exec entries are structurally
    # outside the chunk namespace it walks
    mgr.delete("ck-2")
    assert exec_rels(inner) == before
    mgr.delete("ck-1")  # last checkpoint gone: chunks empty, exec intact
    assert exec_rels(inner) == before
    assert ec.load(ec.key_for("ab" * 32)) is not None


def test_uncommitted_sweep_skips_the_cas_namespace(tmp_path, monkeypatch):
    from determined_clone_tpu.exec.gc_checkpoints import sweep_uncommitted

    mgr, inner = make_cas(tmp_path)
    _, compiled, x = compile_one()
    ec = mgr.exec_cache()
    assert ec.store(ec.key_for("ab" * 32), compiled, program="p")
    before = exec_rels(inner)
    # age floor 0: everything uncommitted is sweepable — including the
    # "cas" storage_id (never committed, no COMMIT marker) if the sweep
    # failed to skip it
    monkeypatch.setenv("DCT_GC_UNCOMMITTED_AGE_S", "0")
    swept = sweep_uncommitted(inner)
    assert swept == 0
    assert exec_rels(inner) == before


def test_storage_stats_splits_namespaces(tmp_path):
    mgr, inner = make_cas(tmp_path)
    src = str(tmp_path / "src")
    write_payload(src, os.urandom(4 * 1024))
    mgr.upload(src, "ck-1")
    _, compiled, x = compile_one()
    ec = mgr.exec_cache()
    assert ec.store(ec.key_for("ab" * 32), compiled, program="p")

    stats = mgr.storage_stats()
    ns = stats["namespaces"]
    assert ns["chunks"]["objects"] == 4
    assert ns["chunks"]["bytes"] == 4 * 1024
    assert ns["exec"]["executables"] == 1
    assert ns["exec"]["objects"] == 1          # content blobs
    assert ns["exec"]["bytes"] > 0
    # the top-level chunk accounting ignores exec blobs entirely
    assert stats["chunk_count"] == 4
    assert stats["chunk_bytes"] == 4 * 1024


def test_exec_cache_stats_by_program(tmp_path):
    cache = make_cache(tmp_path)
    _, compiled, x = compile_one()
    assert cache.store(cache.key_for("ab" * 32), compiled,
                       program="serving_forward", compile_seconds=2.0)
    assert cache.store(cache.key_for("cd" * 32), compiled,
                       program="serving_forward", compile_seconds=3.0)
    assert cache.store(cache.key_for("ef" * 32), compiled,
                       program="train_step", compile_seconds=5.0)
    assert cache.load(cache.key_for("ab" * 32)) is not None
    assert cache.load(cache.key_for("11" * 32)) is None

    stats = cache.stats()
    assert stats["entries"] == 3
    assert stats["blob_count"] >= 1  # identical executables dedup
    assert stats["hit_rate"] == 0.5
    fwd = stats["by_program"]["serving_forward"]
    assert fwd["entries"] == 2 and fwd["compile_seconds"] == 5.0
    assert stats["by_program"]["train_step"]["entries"] == 1
    assert stats["session"]["stores"] == 3


# ---------------------------------------------------------------------------
# warm-start contract
# ---------------------------------------------------------------------------

def test_warm_restart_loads_full_ladder_in_process(tmp_path):
    """Two warmstart legs in one process: the second builds every
    entry point fresh (new jit wrappers, empty jit caches) and must load
    the whole ladder — zero compiles, zero fallbacks, identical greedy
    output, goodput compile collapsed to the deserialize residual."""
    from determined_clone_tpu.serving import warmstart

    d = str(tmp_path / "exec-cache")
    leg1 = warmstart.run(d)
    assert leg1["programs_compiled"] == leg1["program_budget"]
    assert leg1["exec_cache"]["exec_cache_misses"] == \
        leg1["program_budget"]
    assert leg1["exec_cache"]["exec_cache_hits"] == 0

    jax.clear_caches()  # drop tracing caches too: a true cold process
    leg2 = warmstart.run(d)
    assert leg2["programs_compiled"] == leg2["program_budget"]
    assert leg2["exec_cache"]["exec_cache_hits"] == leg2["program_budget"]
    assert leg2["exec_cache"]["exec_cache_misses"] == 0
    assert leg2["exec_cache"]["fallback_compiles"] == 0
    assert leg2["exec_cache"]["compile_time_saved_s"] > 0
    assert leg2["tokens"] == leg1["tokens"]
    assert leg2["goodput_compile_s"] < leg1["goodput_compile_s"]
    assert leg2["exec_cache_metrics"][
        "xla_exec_cache_hits_total"] == leg2["program_budget"]


def run_warmstart_subprocess(cache_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("DCT_FAULT_PLAN", None)
    proc = subprocess.run(
        [sys.executable, "-m", "determined_clone_tpu.serving.warmstart",
         "--exec-cache-dir", cache_dir],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True,
        timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout)


def test_warm_start_subprocess_zero_recompiles(tmp_path):
    """The tentpole's acceptance pin: a genuinely separate second
    process compiles nothing — every ladder program loads from the
    persistent cache — and its greedy decode is bit-identical."""
    d = str(tmp_path / "exec-cache")
    leg1 = run_warmstart_subprocess(d)
    assert leg1["exec_cache"]["exec_cache_misses"] == \
        leg1["program_budget"]

    leg2 = run_warmstart_subprocess(d)
    assert leg2["exec_cache"]["exec_cache_hits"] == leg2["program_budget"]
    assert leg2["exec_cache"]["exec_cache_misses"] == 0
    assert leg2["exec_cache"]["fallback_compiles"] == 0  # jit-cache probe
    assert leg2["programs_compiled"] == leg2["program_budget"]
    assert leg2["tokens"] == leg1["tokens"]
    # the goodput compile category collapses on the warm leg
    assert leg2["goodput_compile_s"] < leg1["goodput_compile_s"]
    assert leg2["warmup_s"] < leg1["warmup_s"]
