"""Control-plane observability: scheduler lifecycle telemetry end to end.

Covers the three contracts of docs/observability.md's control-plane
section against a real C++ master:

- **exposition conformance** — the master's ``GET /metrics`` text parses
  losslessly through ``parse_prometheus_text`` (the same parser `dct
  metrics` uses), with the exact summary shape (quantile children
  0.5/0.95/0.99 + ``_sum``/``_count``), one TYPE line per family, and
  label escaping that round-trips the Python registry's rules;
- **scheduler summary + trace stitching** — ``GET
  /api/v1/cluster/scheduler`` mirrors the counters, and ``dct trace
  export --experiment N`` emits a validated Chrome trace whose master
  lane (submit→schedule→run) temporally encloses the trial lane's first
  ``train_dispatch`` span;
- **synthetic load** — tools/loadgen.py drives thousands of no-op trials
  through simulated agents and reads non-null control-plane numbers back
  (the 10k-trial variant rides the slow marker).
"""
import json
import math
import time
import urllib.request
from pathlib import Path

import pytest

from tests.test_platform import build_binaries, start_master

REPO = Path(__file__).resolve().parent.parent

# an exposition-hostile pool name: quotes, backslashes and a newline all
# must survive the C++ label escaping and the Python un-escaping
UGLY_POOL = 'po"ol\\sla\nsh'

SCHED_COUNTER_FAMILIES = [
    "dct_master_sched_submitted_total",
    "dct_master_sched_scheduled_total",
    "dct_master_sched_running_total",
    "dct_master_sched_completed_total",
    "dct_master_sched_preemptions_total",
    "dct_master_sched_reschedules_total",
    "dct_master_sched_queue_moves_total",
    "dct_master_sched_priority_changes_total",
    "dct_master_sched_decisions_total",
    "dct_master_sched_considered_total",
    "dct_master_sched_gangs_admitted_total",
    "dct_master_sched_gang_wait_ticks_total",
]
SCHED_SUMMARY_FAMILIES = [
    "dct_master_sched_decision_seconds",
    "dct_master_sched_queue_wait_seconds",
    "dct_master_sched_submit_to_running_seconds",
]


@pytest.fixture(scope="module")
def master(tmp_path_factory):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    tmp = tmp_path_factory.mktemp("cplane")
    proc, session, port = start_master(tmp)
    yield {"session": session, "port": port, "proc": proc, "tmp": tmp}
    proc.kill()
    proc.wait(timeout=10)


def req(port, method, path, body=None):
    r = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=30) as resp:
        return json.loads(resp.read() or "{}")


def metrics_text(port):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
        return resp.read().decode("utf-8")


def run_one_trial(port, agent_id, *, span=None, pool="default"):
    """Create a 1-trial custom-searcher experiment, run it to completion
    through a simulated agent, optionally shipping ``span`` (a profiler
    record) while the trial is running. ``pool`` pins the trial to the
    driving agent's pool so it can't land on an earlier test's silent
    agent. Returns (exp_id, trial_id)."""
    exp = req(port, "POST", "/api/v1/experiments", {"config": {
        "name": f"cp-{agent_id}", "entrypoint": "noop:Noop",
        "searcher": {"name": "custom", "metric": "loss"},
        "resources": {"slots_per_trial": 1, "resource_pool": pool},
        "hyperparameters": {}}})["experiment"]
    req(port, "POST", f"/api/v1/experiments/{exp['id']}/searcher/operations",
        {"ops": [{"type": "create", "request_id": 0, "hparams": {}},
                 {"type": "validate_after", "request_id": 0, "units": 1}]})
    trial_id = req(port, "GET",
                   f"/api/v1/experiments/{exp['id']}")["trials"][0]["id"]
    deadline = time.time() + 30
    while time.time() < deadline:
        hb = req(port, "POST", f"/api/v1/agents/{agent_id}/heartbeat",
                 {"exited": [], "running": []})
        starts = [c for c in hb.get("commands", [])
                  if c.get("type") == "start"]
        for cmd in starts:
            aid = cmd["allocation_id"]
            req(port, "POST", f"/api/v1/agents/{agent_id}/task_event",
                {"allocation_id": aid, "event": "running"})
            if span is not None:
                span = dict(span, wall_epoch=time.time())
                req(port, "POST", f"/api/v1/trials/{trial_id}/profiler",
                    {"samples": [span]})
                # the master's "run" leg is running_at→ended_at; ending
                # after the span's wall end keeps the enclosure strict
                time.sleep(span["dur_us"] / 1e6 + 0.05)
            req(port, "POST",
                f"/api/v1/trials/{trial_id}/searcher/completed_op",
                {"metric": 0.0,
                 "units": (cmd.get("trial") or {}).get("target_units", 1)})
            req(port, "POST", f"/api/v1/agents/{agent_id}/task_event",
                {"allocation_id": aid, "event": "exited", "exit_code": 0})
            return exp["id"], trial_id
        time.sleep(0.1)
    raise AssertionError("trial never received a start command")


# ---------------------------------------------------------------------------
# exposition conformance
# ---------------------------------------------------------------------------

class TestExpositionConformance:
    @pytest.fixture(scope="class", autouse=True)
    def seeded(self, master):
        """One agent in an exposition-hostile pool plus one completed
        trial, so every family (queue gauges included) has children."""
        port = master["port"]
        req(port, "POST", "/api/v1/agents/register",
            {"id": "conf-agent", "slots": 2, "topology": "fake-2",
             "address": "127.0.0.1:0", "resource_pool": "default"})
        req(port, "POST", "/api/v1/agents/register",
            {"id": "conf-agent-ugly", "slots": 1, "topology": "fake-1",
             "address": "127.0.0.1:0", "resource_pool": UGLY_POOL})
        run_one_trial(port, "conf-agent")
        # a queued task in the ugly pool keeps its queue-depth gauge live
        master["session"].create_task("command", cmd=["sleep", "9"],
                                      slots=5, resource_pool=UGLY_POOL)
        return port

    def test_parses_with_full_summary_shape(self, master):
        from determined_clone_tpu.telemetry.metrics import (
            parse_prometheus_text,
        )

        text = metrics_text(master["port"])
        parsed = parse_prometheus_text(text)
        for fam in SCHED_COUNTER_FAMILIES:
            assert parsed["types"][fam] == "counter", fam
            assert any(s[0] == fam for s in parsed["samples"]), fam
        for fam in SCHED_SUMMARY_FAMILIES:
            assert parsed["types"][fam] == "summary", fam
            quantiles = {s[1]["quantile"] for s in parsed["samples"]
                         if s[0] == fam and "quantile" in s[1]}
            assert quantiles == {"0.5", "0.95", "0.99"}, fam
            assert any(s[0] == f"{fam}_sum" for s in parsed["samples"])
            counts = [s[2] for s in parsed["samples"]
                      if s[0] == f"{fam}_count"]
            assert counts and all(c == int(c) for c in counts)
            assert parsed["help"].get(fam), f"{fam} has no HELP"

    def test_one_type_line_per_family(self, master):
        text = metrics_text(master["port"])
        seen = {}
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                name = line.split()[2]
                seen[name] = seen.get(name, 0) + 1
        dupes = {n: c for n, c in seen.items() if c > 1}
        assert not dupes, f"duplicate TYPE lines: {dupes}"
        for fam in SCHED_COUNTER_FAMILIES + SCHED_SUMMARY_FAMILIES:
            assert fam in seen, fam

    def test_label_escaping_round_trips(self, master):
        from determined_clone_tpu.telemetry.metrics import (
            parse_prometheus_text,
        )

        text = metrics_text(master["port"])
        assert "\n\n" not in text  # an escaped newline never splits a line
        parsed = parse_prometheus_text(text)
        pools = {s[1].get("pool") for s in parsed["samples"]
                 if s[0] == "dct_master_sched_queue_depth"}
        assert UGLY_POOL in pools, f"ugly pool lost in escaping: {pools}"

    def test_values_round_trip_through_python_renderer(self, master):
        """Lossless cross-language round-trip: every C++ sample, re-rendered
        with the Python registry's own formatter and re-parsed, yields the
        identical value — i.e. the C++ exposition writes numbers exactly
        like telemetry/metrics.py would."""
        from determined_clone_tpu.telemetry.metrics import (
            _fmt,
            _label_str,
            parse_prometheus_text,
        )

        parsed = parse_prometheus_text(metrics_text(master["port"]))
        assert parsed["samples"], "empty exposition"
        rendered = "\n".join(
            f"{name}{_label_str(labels) if labels else ''} {_fmt(value)}"
            for name, labels, value in parsed["samples"]) + "\n"
        reparsed = parse_prometheus_text(rendered)
        assert len(reparsed["samples"]) == len(parsed["samples"])
        for (n1, l1, v1), (n2, l2, v2) in zip(parsed["samples"],
                                              reparsed["samples"]):
            assert (n1, l1) == (n2, l2)
            if math.isnan(v1):
                assert math.isnan(v2)
            else:
                assert v1 == v2, f"{n1}: {v1!r} != {v2!r}"

    def test_aggregator_folds_exposition_into_summary(self, master):
        from determined_clone_tpu.telemetry.aggregate import (
            ClusterMetricsAggregator,
        )
        from determined_clone_tpu.telemetry.metrics import (
            parse_prometheus_text,
        )

        agg = ClusterMetricsAggregator()
        n = agg.ingest_prometheus_text("master", metrics_text(master["port"]))
        assert n > 0
        summary = agg.summary()
        assert summary["counters"].get(
            "dct_master_sched_submitted_total", 0) >= 1
        qs = summary["quantiles"]
        assert "dct_master_sched_decision_seconds" in qs
        assert qs["dct_master_sched_decision_seconds"]["p99"] >= 0
        # and its own dump re-parses: the fold-through is itself conformant
        reparsed = parse_prometheus_text(agg.dump())
        assert any(s[0] == "dct_master_sched_decisions_total"
                   for s in reparsed["samples"])


# ---------------------------------------------------------------------------
# scheduler summary + event ring
# ---------------------------------------------------------------------------

def test_scheduler_summary_tracks_lifecycle(master):
    port = master["port"]
    req(port, "POST", "/api/v1/agents/register",
        {"id": "sum-agent", "slots": 1, "topology": "fake-1",
         "address": "127.0.0.1:0", "resource_pool": "sum-pool"})
    base = req(port, "GET", "/api/v1/cluster/scheduler")
    run_one_trial(port, "sum-agent", pool="sum-pool")
    sched = req(port, "GET", "/api/v1/cluster/scheduler")
    c, b = sched["counters"], base["counters"]
    assert c["submitted"] - b["submitted"] == 1
    assert c["scheduled"] - b["scheduled"] == 1
    assert c["running"] - b["running"] == 1
    assert c["completed"] - b["completed"] == 1
    assert c["decisions"] > b["decisions"]  # the tick kept deciding
    lat = sched["latency"]
    for name in ("decision_seconds", "queue_wait_seconds",
                 "submit_to_running_seconds"):
        assert lat[name]["count"] > 0, name
        assert lat[name]["p50"] >= 0
    assert "queue_depth" in sched["gauges"]
    assert "gang_waiting_by_pool" in sched["gauges"]

    events = req(port, "GET", "/api/v1/cluster/scheduler/events")
    names = [s["name"] for s in events["samples"]]
    for expected in ("submit", "schedule", "running", "end", "decision"):
        assert expected in names, f"no {expected!r} event in ring"
    spans = [s for s in events["samples"] if s.get("name") == "schedule"]
    assert all(s["group"] == "span" and s["process"] == "master"
               and s["wall_epoch"] > 0 for s in spans)


# ---------------------------------------------------------------------------
# trace export: master lane encloses the trial lane
# ---------------------------------------------------------------------------

def test_trace_export_master_lane_encloses_trial_dispatch(master, tmp_path):
    from determined_clone_tpu.cli.cli import main
    from determined_clone_tpu.telemetry.chrome_trace import (
        validate_chrome_trace,
    )

    port = master["port"]
    req(port, "POST", "/api/v1/agents/register",
        {"id": "trace-agent", "slots": 1, "topology": "fake-1",
         "address": "127.0.0.1:0", "resource_pool": "trace-pool"})
    dispatch = {"group": "span", "name": "train_dispatch", "ts_us": 0,
                "dur_us": 200000, "tid": 1, "tname": "main",
                "trace_id": "tr-cplane-1"}
    exp_id, trial_id = run_one_trial(port, "trace-agent", span=dispatch,
                                     pool="trace-pool")

    out = tmp_path / "trace.json"
    rc = main(["-m", f"127.0.0.1:{port}", "trace", "export",
               "--experiment", str(exp_id), "-o", str(out)])
    assert rc == 0
    with open(out) as f:
        trace = json.load(f)
    assert validate_chrome_trace(trace) == []
    lanes = trace["otherData"]["processes"]
    assert "master" in lanes and f"trial-{trial_id}" in lanes
    # the master lane inherited the trial's trace id (DCT_TRACE_ID contract)
    assert trace["otherData"]["trace_ids"] == ["tr-cplane-1"]

    pids = {e["args"]["name"]: e["pid"] for e in trace["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    td = next(e for e in events if e["name"] == "train_dispatch")
    assert td["pid"] == pids[f"trial-{trial_id}"]
    lane = {e["name"]: e for e in events
            if e["pid"] == pids["master"]
            and e.get("args", {}).get("experiment_id") == exp_id}
    assert {"submit", "schedule", "run"} <= set(lane)
    # submit starts before the dispatch, the run leg finishes after it:
    # the master's view of the trial temporally encloses the trial's own
    # first training span
    assert lane["submit"]["ts"] <= td["ts"]
    assert (lane["run"]["ts"] + lane["run"]["dur"]
            >= td["ts"] + td["dur"])
    # legs chain: submit → schedule → run without gaps-in-reverse
    assert lane["submit"]["ts"] <= lane["schedule"]["ts"]
    assert lane["schedule"]["ts"] <= lane["run"]["ts"]


# ---------------------------------------------------------------------------
# synthetic load (tools/loadgen.py)
# ---------------------------------------------------------------------------

def _check_load(result, trials):
    assert not result.get("error"), result
    assert result["submitted"] == trials
    assert result["completed"] == trials
    assert not result["incomplete"]
    assert result["submits_per_sec"] > 0
    assert result["decisions_per_sec"] > 0
    s2r = result["submit_to_running_s"]
    assert s2r["count"] >= trials
    assert s2r["p50"] is not None and s2r["p99"] is not None
    assert result["peak_queue_depth"] > 0


def test_loadgen_smoke():
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    from tools.loadgen import run_load

    result = run_load(trials=80, agents=2, slots_per_agent=4, budget_s=90)
    _check_load(result, 80)


@pytest.mark.slow
def test_loadgen_10k_trials():
    """The 10k-trial synthetic run (ISSUE acceptance): the master stays
    responsive, every trial completes, the reservoirs saturate."""
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    from tools.loadgen import run_load

    result = run_load(trials=10_000, agents=8, slots_per_agent=16,
                      budget_s=480)
    _check_load(result, 10_000)
    print(f"\n[loadgen 10k] {result['submits_per_sec']} submits/s, "
          f"{result['decisions_per_sec']} decisions/s, "
          f"p99 submit→running {result['submit_to_running_s']['p99']:.3f}s, "
          f"peak queue {result['peak_queue_depth']}")
