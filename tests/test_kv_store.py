"""Fleet-wide KV memory hierarchy (docs/serving.md "KV memory
hierarchy"): the host-RAM tier's LRU/byte-budget contract and its CAS
cascade, the ``cas/kv/`` blob tier's every-failure-is-a-plain-miss
integrity story (torn spills, corrupt blobs on disk, double-spill
idempotence), the namespace byte-budget sweep, the prefix-inventory
digest + the router's affinity pre-filter on fake ports, and the
end-to-end warm handoff: a second fleet sharing the tier serves a
previously-seen prefix by promoting blocks instead of re-prefilling —
bit-identically."""
import glob
import os

import jax
import numpy as np
import pytest

from determined_clone_tpu import faults
from determined_clone_tpu.models import gpt
from determined_clone_tpu.serving import (
    BucketSpec,
    KVCacheConfig,
    LeastLoadedRouter,
    ServingFleet,
)
from determined_clone_tpu.serving.kv_cache import PrefixCache
from determined_clone_tpu.serving.kv_store import (
    KVBlockStore,
    PrefixInventory,
    prompt_chain_keys,
)
from determined_clone_tpu.storage.base import SharedFSStorageManager
from determined_clone_tpu.storage.cas import (
    CASStorageManager,
    KVBlobStore,
    namespace_usage,
    sweep_namespace,
)

CFG = gpt.GPTConfig(vocab_size=97, n_layers=2, d_model=32, n_heads=4,
                    d_ff=64, max_seq_len=48, remat=False,
                    attention_impl="mha")
BUCKETS = BucketSpec.build(2, 16)
CACHE = KVCacheConfig(num_blocks=16, block_size=8)
MAX_NEW = 6
# exactly two full KV blocks of shared prefix: a fully-covered prompt
# exercises the COW fork of the final shared block (the engine always
# re-scores the last prompt token). The shapes compiled here must stay a
# subset of tests/test_serving.py's ladder — the jit cache is keyed on
# the underlying forward and shared process-wide, and that module
# asserts its exact size.
PROMPT = [5, 9, 2, 7, 4, 8, 3, 6, 11, 13, 17, 19, 23, 29, 31, 37]


@pytest.fixture(scope="module")
def params():
    return gpt.init(jax.random.PRNGKey(0), CFG)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def payload(seed: int, nbytes: int = 1024) -> dict:
    rng = np.random.default_rng(seed)
    half = nbytes // 2
    return {"k": rng.standard_normal(half // 8).astype(np.float64),
            "v": rng.standard_normal(half // 8).astype(np.float64)}


def make_fleet(params, **kw):
    kw.setdefault("buckets", BUCKETS)
    kw.setdefault("cache", CACHE)
    kw.setdefault("warmup", False)
    kw.setdefault("prefix_cache", True)
    return ServingFleet(params, CFG, **kw)


# -- chain keys + inventory (pure units) ------------------------------------

def test_prompt_chain_keys_match_prefix_cache_chain():
    """The router hashes prompts with the same chain the prefix cache
    uses to key blocks — otherwise affinity coverage is always zero."""
    prompt = list(range(1, 20))
    keys = prompt_chain_keys(prompt, 8, 8)
    assert len(keys) == 2  # 19 tokens -> 2 full blocks of 8
    prev = b""
    for i, k in enumerate(keys):
        prev = PrefixCache._chain(prev, prompt[i * 8:(i + 1) * 8])
        assert k == prev.hex()
    # fewer than one full block -> no keys; max_blocks caps the depth
    assert prompt_chain_keys([1, 2, 3], 8, 8) == []
    assert len(prompt_chain_keys(list(range(64)), 8, 3)) == 3


def test_prefix_inventory_coverage_and_roundtrip():
    keys = [f"{i:02x}" * 32 for i in range(40)]
    inv = PrefixInventory.build(keys, top_k=32)
    # exact top-K and bloom overflow are both one-sided: no false
    # negatives for any key that went in
    assert all(inv.covers(k) for k in keys)
    # coverage_depth counts the LEADING covered run — a missed root
    # zeroes it even if deeper keys are resident
    assert inv.coverage_depth(keys[:5]) == 5
    doc = inv.to_dict()
    back = PrefixInventory.from_dict(doc)
    assert back.coverage_depth(keys[:7]) == 7
    assert PrefixInventory.build([]).coverage_depth(keys[:3]) == 0


# -- host tier (KVBlockStore) -----------------------------------------------

def test_host_tier_budget_evicts_lru_under_churn():
    store = KVBlockStore(budget_bytes=4096)
    fp = "fp0"
    for i in range(12):  # ~1 KiB each into a 4 KiB budget
        store.put(fp, f"{i:02d}" * 16, payload(i))
    st = store.stats()
    assert st["bytes"] <= 4096
    assert st["host_evictions"] >= 8
    assert st["entries"] + st["host_evictions"] == st["puts"]
    # without a CAS tier the evicted entries are gone: plain misses
    assert store.get(fp, "00" * 16) is None
    assert store.stats()["misses"] == 1
    # survivors are exact
    got = store.get(fp, "11" * 16)
    assert got is not None
    np.testing.assert_array_equal(got["k"], payload(11)["k"])


def test_host_tier_duplicate_put_is_idempotent():
    store = KVBlockStore(budget_bytes=1 << 20)
    store.put("fp", "aa", payload(1))
    store.put("fp", "aa", payload(1))
    st = store.stats()
    assert st["puts"] == 1 and st["duplicate_puts"] == 1
    assert st["entries"] == 1


def test_host_tier_keys_are_mru_first_per_fingerprint():
    store = KVBlockStore(budget_bytes=1 << 20)
    for hx in ("aa", "bb", "cc"):
        store.put("fp1", hx, payload(0, 64))
    store.put("fp2", "dd", payload(0, 64))
    store.get("fp1", "aa")  # touch -> most recent
    assert store.keys("fp1") == ["aa", "cc", "bb"]
    assert store.keys("fp2") == ["dd"]


def test_host_tier_cascades_to_cas_and_promotes_back(tmp_path):
    inner = SharedFSStorageManager(str(tmp_path))
    blobs = KVBlobStore(inner)
    store = KVBlockStore(budget_bytes=2048, blob_store=blobs)
    fp = "fp0"
    for i in range(6):
        store.put(fp, f"{i:02d}" * 16, payload(i))
    st = store.stats()
    assert st["cas_spills"] == st["host_evictions"] > 0
    # the evicted root is served from cas/kv/ and re-inserted host-side
    got = store.get(fp, "00" * 16)
    assert got is not None
    np.testing.assert_array_equal(got["v"], payload(0)["v"])
    assert store.stats()["cas_hits"] == 1
    assert store.contains(fp, "00" * 16)  # re-inserted


# -- CAS tier (cas/kv/) -----------------------------------------------------

def test_cas_kv_double_spill_is_idempotent(tmp_path):
    blobs = KVBlobStore(SharedFSStorageManager(str(tmp_path)))
    key = {"fingerprint": "fp", "chain": "ab" * 32}
    assert blobs.store(key, payload(3)) is True
    assert blobs.store(key, payload(3)) is True
    assert blobs.session["stores"] == 1
    assert blobs.session["duplicate_stores"] == 1
    assert blobs.stats()["entries"] == 1


def test_cas_kv_torn_spill_is_a_plain_miss(tmp_path):
    """An injected torn write lands truncated bytes under the full
    digest's key; the fetch-side sha256 check convicts and the reader
    sees a plain miss — never wrong K/V."""
    blobs = KVBlobStore(SharedFSStorageManager(str(tmp_path)))
    key = {"fingerprint": "fp", "chain": "cd" * 32}
    plan = faults.activate(faults.plan_from_dict({"rules": [
        {"point": "kv_store.spill", "action": "truncate",
         "keep_bytes": 7, "times": 1}]}))
    try:
        blobs.store(key, payload(4))
    finally:
        faults.deactivate(plan)
    assert blobs.load(key) is None
    assert blobs.session["misses"] >= 1
    assert blobs.session["errors"] >= 1
    # the miss is recoverable: a clean re-spill serves exact bytes.
    # (the torn blob squatted on the full digest's key; the CAS put
    # dedups against it, so the re-spill must still convict at fetch)
    blobs2 = KVBlobStore(SharedFSStorageManager(str(tmp_path) + "-2"))
    assert blobs2.store(key, payload(4)) is True
    got = blobs2.load(key)
    np.testing.assert_array_equal(got["k"], payload(4)["k"])


def test_cas_kv_corrupt_blob_on_disk_is_a_plain_miss(tmp_path):
    blobs = KVBlobStore(SharedFSStorageManager(str(tmp_path)))
    key = {"fingerprint": "fp", "chain": "ef" * 32}
    assert blobs.store(key, payload(5)) is True
    assert blobs.load(key) is not None
    paths = [p for p in glob.glob(str(tmp_path) + "/**/kv/blobs/**",
                                  recursive=True) if os.path.isfile(p)]
    assert paths, "expected a blob file under cas/kv/blobs/"
    with open(paths[0], "r+b") as f:
        f.truncate(11)  # torn on disk after a clean spill
    assert blobs.load(key) is None
    assert blobs.session["errors"] >= 1


def test_cas_kv_index_without_blob_is_a_plain_miss(tmp_path):
    blobs = KVBlobStore(SharedFSStorageManager(str(tmp_path)))
    key = {"fingerprint": "fp", "chain": "0f" * 32}
    assert blobs.store(key, payload(6)) is True
    for p in glob.glob(str(tmp_path) + "/**/kv/blobs/**", recursive=True):
        if os.path.isfile(p):
            os.unlink(p)
    assert blobs.load(key) is None


# -- namespace budget sweep -------------------------------------------------

def test_sweep_namespace_enforces_kv_budget(tmp_path):
    inner = SharedFSStorageManager(str(tmp_path))
    blobs = KVBlobStore(inner)
    for i in range(8):
        blobs.store({"fingerprint": "fp", "chain": f"{i:02d}" * 32},
                    payload(i, 4096))
    before = sum(namespace_usage(inner, "kv").values())
    res = sweep_namespace(inner, "kv", before // 2)
    assert res["swept"] is True
    assert res["evicted"] > 0
    assert res["bytes"] <= before // 2
    # a swept entry is a plain miss; survivors still serve
    hits = sum(
        blobs.load({"fingerprint": "fp", "chain": f"{i:02d}" * 32})
        is not None for i in range(8))
    assert 0 < hits < 8


def test_manager_namespace_budgets_and_stats(tmp_path):
    inner = SharedFSStorageManager(str(tmp_path))
    mgr = CASStorageManager(inner, namespace_budgets={"kv": 8192})
    kv = mgr.kv_store()
    assert kv.budget_bytes == 8192  # inherits the manager's budget
    for i in range(8):
        kv.store({"fingerprint": "fp", "chain": f"{i:02d}" * 32},
                 payload(i, 4096))
    swept = mgr.sweep_namespaces()
    assert swept["kv"]["swept"] is True and swept["kv"]["evicted"] > 0
    stats = mgr.storage_stats()
    ns = stats["namespaces"]["kv"]
    assert ns["bytes"] <= 8192
    assert ns["evictions"] == swept["kv"]["evicted"]
    # chunk GC / checkpoint accounting never counts kv objects
    assert stats["chunk_count"] == 0


# -- router affinity (fake ports) -------------------------------------------

class FakePort:
    def __init__(self, rid, queue=0, free=16, inventory=None):
        self.replica_id = rid
        self.queue = queue
        self.free = free
        self.admit = True
        self.inventory = inventory

    def admitting(self):
        return self.admit

    def load(self):
        return (self.queue, -self.free)

    def prefix_inventory(self):
        return self.inventory


def test_router_affinity_steers_within_slack():
    prompt = list(range(1, 25))
    keys = prompt_chain_keys(prompt, 8, 8)
    warm = PrefixInventory.build(keys).to_dict()
    r = LeastLoadedRouter(prefix_block_size=8, affinity_queue_slack=2)
    cold = FakePort("a-cold", queue=0, free=16)
    hot = FakePort("b-warm", queue=1, free=16, inventory=warm)
    r.add(cold)
    r.add(hot)
    # coverage wins inside the slack band even against a shorter queue
    assert r.pick(prompt=prompt).replica_id == "b-warm"
    assert r.registry.counter(
        "router_affinity_picks_total", "").value == 1
    # ... but never overrides overload: outside the band, least-loaded
    hot.queue = 3
    assert r.pick(prompt=prompt).replica_id == "a-cold"
    # no prompt / affinity off -> the plain least-loaded contract
    assert r.pick().replica_id == "a-cold"
    r2 = LeastLoadedRouter()  # prefix_block_size=0: affinity disarmed
    r2.add(hot)
    r2.add(cold)
    hot.queue = 0
    assert r2.pick(prompt=prompt).replica_id == "a-cold"


def test_router_affinity_zero_coverage_falls_back():
    prompt = list(range(1, 25))
    r = LeastLoadedRouter(prefix_block_size=8)
    a = FakePort("a", queue=1, free=2)
    b = FakePort("b", queue=1, free=9)
    r.add(a)
    r.add(b)
    # nobody advertises coverage (inventory None): free blocks break
    # the tie exactly as without affinity
    assert r.pick(prompt=prompt).replica_id == "b"
    assert r.registry.counter(
        "router_affinity_picks_total", "").value == 0


# -- end-to-end: promotion is bit-exact, restarts warm from the tier --------

def test_fleet_kv_store_requires_prefix_cache(params):
    with pytest.raises(ValueError, match="prefix_cache"):
        ServingFleet(params, CFG, buckets=BUCKETS, cache=CACHE,
                     prefix_cache=False, kv_store=True)


def test_warm_handoff_promotes_bit_identical(params, tmp_path):
    """The acceptance path in miniature: fleet A serves a prompt and
    flushes to a CAS-backed tier; a brand-new fleet B sharing the tier
    serves the same prompt by PROMOTING the shared blocks (zero misses
    on the shared prefix) and emits bit-identical greedy tokens."""
    blobs = KVBlobStore(SharedFSStorageManager(str(tmp_path)))
    store = KVBlockStore(budget_bytes=32 << 20, blob_store=blobs)

    fleet_a = make_fleet(params, name="kv-a", kv_store=store)
    try:
        fleet_a.scale_up(1)
        ref, _ = fleet_a.handle_request(PROMPT, MAX_NEW, timeout=60.0)
        ref_tokens = list(ref.tokens)
    finally:
        fleet_a.close()  # close() flushes resident blocks to the tier
    assert store.stats()["puts"] >= 2  # both full prompt blocks landed

    fleet_b = make_fleet(params, name="kv-b", kv_store=store)
    try:
        fleet_b.scale_up(1)
        res, _ = fleet_b.handle_request(PROMPT, MAX_NEW, timeout=60.0)
        assert list(res.tokens) == ref_tokens
        st = fleet_b.replicas()[0].engine.stats()
        assert st.kv_promoted_blocks >= 2
        assert st.kv_miss_blocks == 0
        assert st.kv_host_hit_blocks + st.kv_cas_hit_blocks >= 2
        rollup_src = fleet_b.stats()
    finally:
        fleet_b.close()
    assert rollup_src is not None
    assert store.stats()["hit_rate"] is not None


def test_replace_replica_flushes_then_replacement_warms(params):
    """stop/replace teardown demotes resident blocks; the replacement
    promotes them back on its first shared-prefix request."""
    store = KVBlockStore(budget_bytes=32 << 20)
    fleet = make_fleet(params, name="kv-r", kv_store=store)
    try:
        ids = fleet.scale_up(1)
        fleet.handle_request(PROMPT, MAX_NEW, timeout=60.0)
        for rep in fleet.replicas():
            rep.engine.wait_idle(15.0)
        replacement = fleet.replace_replica(ids[0], reason="test")
        assert store.stats()["puts"] >= 2
        res, _ = fleet.handle_request(PROMPT, MAX_NEW, timeout=60.0)
        assert res is not None
        st = [r.engine.stats() for r in fleet.replicas()
              if r.replica_id in replacement][0]
        assert st.kv_promoted_blocks >= 2
        assert st.kv_miss_blocks == 0
    finally:
        fleet.close()


@pytest.mark.slow
def test_chaos_kv_warm_failover_scenario(params):
    """The full seeded chaos scenario: mid-burst replace + drain, the
    replacement warms from the tier with zero tier misses, outputs
    bit-identical, zero leaked blocks."""
    from determined_clone_tpu.serving.chaos import run_scenarios
    (result,) = run_scenarios(["kv_warm_failover"], seed=0,
                              params=params)
    failed = [c.name + ": " + c.detail for c in result.checks
              if not c.ok]
    assert result.passed, failed
