"""Kubernetes resource manager: allocations become TPU pods.

Drives a C++ master started with --rm kubernetes (dry-run kubectl seam:
the "cluster" is <data-dir>/kube_state/pods.json; this test plays kubelet
by flipping pod phases) — ≈ the reference's kubernetesrm tests over mocked
pods services (master/internal/rm/kubernetesrm/pods_test.go).
"""
import json
import time
from pathlib import Path

import pytest

from tests.test_platform import build_binaries, start_master

EXP_CONFIG = {
    "name": "kube-exp",
    "entrypoint": "model:Trial",
    "searcher": {"name": "single", "metric": "loss",
                 "max_length": {"batches": 1}},
    "resources": {"slots_per_trial": 16, "topology": "v5e-16"},
}


def wait_for(predicate, timeout=30, interval=0.1, desc="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    pytest.fail(f"timed out waiting for {desc}")


class KubeSim:
    """The test's kubelet: reads/writes the dry-run seam's pods.json."""

    def __init__(self, data_dir: Path):
        self.path = data_dir / "kube_state" / "pods.json"

    def pods(self):
        if not self.path.exists():
            return []
        return json.loads(self.path.read_text() or "[]")

    def set_phase(self, phase, ip_base="10.0.0.", exit_code=0,
                  only_name=None):
        pods = self.pods()
        for i, p in enumerate(pods):
            if only_name and p["name"] != only_name:
                continue
            p["phase"] = phase
            p["ip"] = f"{ip_base}{i + 1}"
            p["exit_code"] = exit_code
        self.path.write_text(json.dumps(pods))


def complete_searcher_op(session, exp_id):
    """Play the in-pod harness: report the searcher op's validation so the
    trial's clean exit closes it (pods run no real harness in dry-run)."""
    trial = session.get_experiment(exp_id)["trials"][0]
    session.post(f"/api/v1/trials/{trial['id']}/searcher/completed_op",
                 {"metric": 0.1, "units": trial["target_units"]})


@pytest.fixture()
def kube_master(tmp_path):
    if not build_binaries():
        pytest.skip("C++ master build unavailable")
    proc, session, port = start_master(
        tmp_path, "--rm", "kubernetes", "--kube-master-host", "127.0.0.1",
        "--kube-slots-per-pod", "8", "--kube-namespace", "tpu-ns")
    sim = KubeSim(tmp_path / "master-data")
    yield {"proc": proc, "session": session, "port": port,
           "tmp": tmp_path, "sim": sim}
    proc.kill()
    proc.wait(timeout=10)


def test_allocation_becomes_tpu_pods(kube_master):
    session, sim = kube_master["session"], kube_master["sim"]
    exp = session.create_experiment(EXP_CONFIG)

    # 16 chips at 8 chips/pod -> a 2-pod gang
    pods = wait_for(lambda: len(sim.pods()) == 2 and sim.pods(),
                    desc="2 pods submitted")
    names = {p["name"] for p in pods}
    assert all(n.startswith("dct-trial-") for n in names)

    # pod spec: TPU resource limits, GKE selectors, DCT env, trial command
    m = pods[0]["manifest"]
    assert m["kind"] == "Pod" and m["metadata"]["namespace"] == "tpu-ns"
    assert m["metadata"]["labels"]["dct-managed"] == "true"
    spec = m["spec"]
    assert spec["restartPolicy"] == "Never"
    sel = spec["nodeSelector"]
    assert sel["cloud.google.com/gke-tpu-accelerator"]
    assert sel["cloud.google.com/gke-tpu-topology"] == "v5e-16"
    c = spec["containers"][0]
    assert c["resources"]["limits"]["google.com/tpu"] == "8"
    assert c["command"][:3] == ["python", "-m",
                                "determined_clone_tpu.exec.trial"]
    env = {e["name"]: e["value"] for e in c["env"]}
    assert env["DCT_MASTER_HOST"] == "127.0.0.1"
    assert env["DCT_MASTER_PORT"] == str(kube_master["port"])
    assert env["DCT_WORLD_SIZE"] == "2"
    assert env["DCT_SLOTS"] == "8"
    assert env["DCT_ALLOC_TOKEN"]
    assert env["DCT_RANK"] in ("0", "1")

    # allocation is Pulling while pods are Pending
    exp_state = session.get_experiment(exp["id"])
    assert exp_state["trials"][0]["state"] in ("QUEUED", "PULLING")

    # kubelet: pods come up -> allocation Running
    sim.set_phase("Running")
    wait_for(lambda: session.get_experiment(exp["id"])["trials"][0]["state"]
             == "RUNNING", desc="trial running")

    # kubelet: pods finish -> experiment completes, pods deleted
    complete_searcher_op(session, exp["id"])
    sim.set_phase("Succeeded")
    wait_for(lambda: session.get_experiment(exp["id"])["experiment"]["state"]
             == "COMPLETED", desc="experiment completed")
    wait_for(lambda: sim.pods() == [], desc="pods garbage-collected")


def test_pod_failure_restarts_trial(kube_master):
    session, sim = kube_master["session"], kube_master["sim"]
    config = dict(EXP_CONFIG)
    config["name"] = "kube-fail"
    config["resources"] = {"slots_per_trial": 8}
    config["max_restarts"] = 1
    exp = session.create_experiment(config)

    pods = wait_for(lambda: sim.pods(), desc="pod submitted")
    first_gen = {p["name"] for p in pods}
    sim.set_phase("Running")
    wait_for(lambda: session.get_experiment(exp["id"])["trials"][0]["state"]
             == "RUNNING", desc="running")
    sim.set_phase("Failed", exit_code=137)

    # trial restarts: a fresh allocation leg -> a fresh pod generation
    def new_generation():
        pods_now = sim.pods()
        return pods_now and {p["name"] for p in pods_now} != first_gen
    wait_for(new_generation, desc="restart pods")
    assert session.get_experiment(exp["id"])["trials"][0]["restarts"] == 1

    # second failure exhausts max_restarts -> experiment errored
    sim.set_phase("Running")
    time.sleep(0.3)
    sim.set_phase("Failed", exit_code=137)
    wait_for(lambda: session.get_experiment(exp["id"])["experiment"]["state"]
             == "ERRORED", desc="experiment errored")


def test_kill_deletes_pods(kube_master):
    session, sim = kube_master["session"], kube_master["sim"]
    config = dict(EXP_CONFIG)
    config["name"] = "kube-kill"
    config["resources"] = {"slots_per_trial": 8}
    exp = session.create_experiment(config)
    wait_for(lambda: sim.pods(), desc="pod submitted")
    sim.set_phase("Running")
    wait_for(lambda: session.get_experiment(exp["id"])["trials"][0]["state"]
             == "RUNNING", desc="running")
    session.kill_experiment(exp["id"])

    # kill is graceful: the master raises the preempt flag; the in-pod
    # harness checkpoints and exits (here: the kubelet sim marks the pods
    # finished), and only then are the pods garbage-collected
    trial = session.get_experiment(exp["id"])["trials"][0]
    alloc_id = f"trial-{trial['id']}.{trial['restarts']}"
    wait_for(lambda: session.get(
        f"/api/v1/allocations/{alloc_id}/preempt")["preempt"],
        desc="preempt flag raised")
    sim.set_phase("Succeeded")
    wait_for(lambda: sim.pods() == [], desc="pods deleted on kill")
    assert session.get_experiment(exp["id"])["experiment"]["state"] == \
        "CANCELED"


def test_reattach_after_master_restart(kube_master):
    session, sim = kube_master["session"], kube_master["sim"]
    config = dict(EXP_CONFIG)
    config["name"] = "kube-reattach"
    config["resources"] = {"slots_per_trial": 8}
    exp = session.create_experiment(config)
    wait_for(lambda: sim.pods(), desc="pod submitted")
    sim.set_phase("Running")
    wait_for(lambda: session.get_experiment(exp["id"])["trials"][0]["state"]
             == "RUNNING", desc="running")

    kube_master["proc"].terminate()
    kube_master["proc"].wait(timeout=10)
    assert sim.pods(), "pods must survive a master restart"

    proc, session, port = start_master(
        kube_master["tmp"], "--rm", "kubernetes", "--kube-master-host",
        "127.0.0.1", "--kube-slots-per-pod", "8")
    kube_master.update(proc=proc, session=session, port=port)

    # restored master re-adopts the running pods instead of resubmitting
    wait_for(lambda: session.get_experiment(exp["id"])["trials"][0]["state"]
             == "RUNNING", desc="reattached running")
    assert len(sim.pods()) == 1

    # and the task can still finish normally
    complete_searcher_op(session, exp["id"])
    sim.set_phase("Succeeded")
    wait_for(lambda: session.get_experiment(exp["id"])["experiment"]["state"]
             == "COMPLETED", desc="completed after reattach")


def test_pods_vanishing_requeues_allocation(kube_master):
    session, sim = kube_master["session"], kube_master["sim"]
    config = dict(EXP_CONFIG)
    config["name"] = "kube-vanish"
    config["resources"] = {"slots_per_trial": 8}
    exp = session.create_experiment(config)
    pods = wait_for(lambda: sim.pods(), desc="pod submitted")
    first_gen = {p["name"] for p in pods}
    sim.set_phase("Running")
    wait_for(lambda: session.get_experiment(exp["id"])["trials"][0]["state"]
             == "RUNNING", desc="running")

    # out-of-band deletion (node reclaim): pods disappear without exiting
    sim.path.write_text("[]")

    # silent retry: the allocation requeues and new pods are submitted,
    # with no restart charged (no real task exit happened)
    def resubmitted():
        pods_now = sim.pods()
        return pods_now and {p["name"] for p in pods_now} == first_gen
    wait_for(resubmitted, desc="pods resubmitted")
    assert session.get_experiment(exp["id"])["trials"][0]["restarts"] == 0
