#!/bin/bash
# Run the test suite on a virtual 8-device CPU mesh.
#
# PALLAS_AXON_POOL_IPS is cleared so the axon TPU-tunnel sitecustomize skips
# its PJRT relay handshake (it serializes every python process behind the
# single TPU grant, ~minutes of startup latency); tests are CPU-only anyway.
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}" \
    python -m pytest "${@:-tests/}" -q
