#!/bin/bash
# Run the test suite on a virtual 8-device CPU mesh.
#
# PALLAS_AXON_POOL_IPS is cleared so the axon TPU-tunnel sitecustomize skips
# its PJRT relay handshake (it serializes every python process behind the
# single TPU grant, ~minutes of startup latency); tests are CPU-only anyway.
#
# `./run_tests.sh --tier1` runs the tier-1 gate subset (everything not
# marked slow) — the same selection ROADMAP.md's verify command uses, and
# the set the prefetch/fused-dispatch tests (tests/test_prefetch_fused.py)
# ride in.
#
# `./run_tests.sh --observability` runs just the telemetry + profiler
# surface (docs/observability.md): the telemetry core, profiler/tensorboard
# shipping, the observability config round-trip, the XLA/device lane +
# flight recorder + goodput ledger + bench result schema, and the static
# checks. The goodput suite skips cleanly under DCT_TELEMETRY_DISABLED=1.
#
# `./run_tests.sh --lint` runs the dctlint static-analysis suite over the
# tier-1 lint set (docs/static_analysis.md) — the same run
# tests/test_static_checks.py gates in CI.
#
# `./run_tests.sh --chaos` runs the fault-tolerance + flight-recorder +
# goodput-ledger + fleet self-healing suites (docs/fault_tolerance.md)
# with no marker filter, so the slow kill -9 subprocess tests (including
# the restart-leg ledger merge) and the full chaos-conductor scenario
# catalog (tools/chaosfleet.py) run too — the tier-1 lane skips them via
# `-m "not slow"`.
#
# `./run_tests.sh --storage` runs the checkpoint-storage surface
# (docs/checkpoint_storage.md): backends, the content-addressed store +
# transfer pool, the persistent executable cache, and the storage-facing
# fault-tolerance paths.
#
# `./run_tests.sh --control-plane` runs the control-plane observability
# surface (docs/observability.md): scheduler lifecycle telemetry,
# exposition conformance, trace stitching with the master lane, the
# job-queue counter checks and the synthetic load harness. Every test in
# the lane skips cleanly when the C++ master build is unavailable.
#
# `./run_tests.sh --serving` runs the online-inference surface
# (docs/serving.md): the continuous-batching engine, paged-KV parity and
# compile discipline, the raw-speed features (COW prefix sharing,
# speculative decoding, chunked prefill), the HTTP surface, the
# KV-cached decode FLOPs accounting, and the batch-inference
# dropped-example counter.
#
# `./run_tests.sh --fleet` runs the serving-fleet surface (docs/serving.md
# "Replica fleets"): the least-loaded router + 429 failover, the drain
# protocol and drain-protected scale-down, blue-green rollout parity, the
# queue-driven autoscaler, the fleet HTTP/CLI surface and the aggregator
# rollup — plus the single-engine suite the fleet builds on, and the
# KV memory hierarchy (host/CAS tier, prefix-affinity routing). The master
# integration tests skip cleanly when the C++ build is unavailable.
#
# `./run_tests.sh --multichip` runs the mesh-observability surface
# (docs/parallelism.md) on the simulated 8-device mesh: collective
# accounting, straggler detection, per-device lanes, the MULTICHIP
# artifact schema, plus the sharding/mesh suites the lane builds on.
# The live-mesh tests skip cleanly when device forcing is unavailable
# (they check len(jax.devices()) themselves).
#
# `./run_tests.sh --bench-gate` compares the two newest BENCH_r*.json
# rounds via tools/bench_gate.py (default -5% samples/sec tolerance; the
# new round must carry a non-null mfu — docs/observability.md).
if [ "$1" = "--bench-gate" ]; then
    shift
    exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python tools/bench_gate.py "$@"
elif [ "$1" = "--lint" ]; then
    shift
    exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python -m tools.dctlint determined_clone_tpu tools bench.py "$@"
elif [ "$1" = "--tier1" ]; then
    shift
    set -- tests/ -m "not slow" "$@"
elif [ "$1" = "--chaos" ]; then
    shift
    set -- tests/test_fault_tolerance.py tests/test_flight_recorder.py \
        tests/test_goodput.py tests/test_self_healing.py "$@"
elif [ "$1" = "--storage" ]; then
    shift
    set -- tests/test_storage_backends.py tests/test_cas_store.py \
        tests/test_exec_cache.py \
        tests/test_fault_tolerance.py -m "not slow" "$@"
elif [ "$1" = "--control-plane" ]; then
    shift
    set -- tests/test_control_plane.py tests/test_load_smoke.py \
        tests/test_job_queue.py \
        -m "not slow" "$@"
elif [ "$1" = "--serving" ]; then
    shift
    set -- tests/test_serving.py tests/test_serving_speed.py \
        tests/test_batch_inference.py \
        -m "not slow" "$@"
elif [ "$1" = "--fleet" ]; then
    shift
    set -- tests/test_serving_fleet.py tests/test_serving.py \
        tests/test_self_healing.py tests/test_kv_store.py \
        -m "not slow" "$@"
elif [ "$1" = "--multichip" ]; then
    shift
    set -- tests/test_mesh_observability.py tests/test_mesh_sharding.py \
        tests/test_xla_telemetry.py tests/test_device_telemetry.py \
        -m "not slow" "$@"
elif [ "$1" = "--observability" ]; then
    shift
    set -- tests/test_telemetry.py tests/test_profiler_tensorboard.py \
        tests/test_observability_config.py tests/test_observability_plane.py \
        tests/test_xla_telemetry.py tests/test_device_telemetry.py \
        tests/test_flight_recorder.py tests/test_goodput.py \
        tests/test_request_tracing.py tests/test_slo.py \
        tests/test_tsdb_rules.py \
        tests/test_bench_schema.py tests/test_static_checks.py \
        -m "not slow" "$@"
fi
exec env PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8 ${XLA_FLAGS:-}" \
    python -m pytest "${@:-tests/}" -q
