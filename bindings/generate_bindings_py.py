#!/usr/bin/env python3
"""Generate the typed Python client from the proto definitions.

≈ the reference's bindings/generate_bindings_py.py (swagger →
harness/determined/common/api/bindings.py), re-done proto-first: protoc
compiles proto/dct/api/v1 into a FileDescriptorSet, this script walks it
with the protobuf runtime and emits determined_clone_tpu/api/bindings.py —
dataclass messages with snake_case JSON (de)serialization plus one request
function per RPC, bound to the REST gateway via the http.proto options.

Usage: python bindings/generate_bindings_py.py [--check]
  --check  regenerate to a buffer and fail if the checked-in file differs
           (the CI drift gate; ≈ the reference's bindings "make check").
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PROTO_DIR = os.path.join(REPO, "proto")
OUT_PATH = os.path.join(REPO, "determined_clone_tpu", "api", "bindings.py")

# field numbers of the custom MethodOptions in dct/api/v1/http.proto
HTTP_METHOD_FIELD = 50001
HTTP_PATH_FIELD = 50002
HTTP_POLL_STREAM_FIELD = 50003

SCALAR_TYPES = {
    1: ("float", "0.0"),   # double
    2: ("float", "0.0"),   # float
    3: ("int", "0"),       # int64
    4: ("int", "0"),       # uint64
    5: ("int", "0"),       # int32
    8: ("bool", "False"),  # bool
    9: ("str", '""'),      # string
    13: ("int", "0"),      # uint32
}
TYPE_MESSAGE = 11
LABEL_REPEATED = 3

WELL_KNOWN_ANY = {
    ".google.protobuf.Struct": "dict",
    ".google.protobuf.Value": "object",
}


def compile_descriptors() -> bytes:
    with tempfile.NamedTemporaryFile(suffix=".pb") as tmp:
        subprocess.run(
            ["protoc", f"-I{PROTO_DIR}",
             f"--descriptor_set_out={tmp.name}", "--include_imports",
             os.path.join(PROTO_DIR, "dct", "api", "v1", "api.proto")],
            check=True,
        )
        tmp.seek(0)
        return tmp.read()


def snake(name: str) -> str:
    out = []
    for i, c in enumerate(name):
        if c.isupper() and i > 0 and (not name[i - 1].isupper() or
                                      (i + 1 < len(name) and
                                       name[i + 1].islower())):
            out.append("_")
        out.append(c.lower())
    return "".join(out)


def parse_method_options(options) -> dict:
    """Read the raw custom options (unknown to the runtime's descriptor pool)
    out of the serialized MethodOptions."""
    raw = options.SerializeToString()
    out = {}
    i = 0
    while i < len(raw):
        tag, i = _read_varint(raw, i)
        field, wire = tag >> 3, tag & 7
        if wire == 2:  # length-delimited
            length, i = _read_varint(raw, i)
            val = raw[i:i + length]
            i += length
            if field == HTTP_METHOD_FIELD:
                out["method"] = val.decode()
            elif field == HTTP_PATH_FIELD:
                out["path"] = val.decode()
        elif wire == 0:
            val, i = _read_varint(raw, i)
            if field == HTTP_POLL_STREAM_FIELD:
                out["stream"] = bool(val)
        else:  # pragma: no cover - no other wire types in MethodOptions
            raise ValueError(f"unexpected wire type {wire}")
    return out


def _read_varint(buf: bytes, i: int):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not (b & 0x80):
            return val, i
        shift += 7


def py_type(field) -> tuple:
    """(annotation, default_expr, from_json_expr(v), to_json_expr(x)).

    Scalars use a None sentinel (proto3 "explicit presence"): unset fields
    serialize to nothing, while an explicit zero/empty value round-trips —
    so e.g. priority=0 is expressible and distinct from "use the server
    default"."""
    if field.type == TYPE_MESSAGE:
        if field.type_name in WELL_KNOWN_ANY:
            base = WELL_KNOWN_ANY[field.type_name]
            conv_in = "v"
            conv_out = "x"
        else:
            base = "V1" + field.type_name.split(".")[-1]
            conv_in = f"{base}.from_json(v)"
            conv_out = "x.to_json()"
        if field.label == LABEL_REPEATED:
            return (f"Optional[List[{base}]]", "None",
                    f"[{conv_in} for v in (v or [])]",
                    f"[{conv_out} for x in x]")
        return (f"Optional[{base}]", "None",
                f"({conv_in} if v is not None else None)",
                f"({conv_out} if x is not None else None)")
    ann, _ = SCALAR_TYPES[field.type]
    if field.label == LABEL_REPEATED:
        return (f"Optional[List[{ann}]]", "None",
                f"[{ann}(v) for v in (v or [])]", "list(x)")
    return (f"Optional[{ann}]", "None",
            f"{ann}(v)" if ann != "bool" else "bool(v)", "x")


def gen_message(msg) -> str:
    name = "V1" + msg.name
    lines = [f"@dataclasses.dataclass", f"class {name}:"]
    if not msg.field:
        lines.append("    pass")
    inits = []
    froms = []
    tos = []
    for field in msg.field:
        ann, default, from_expr, to_expr = py_type(field)
        repeated = ann.startswith("Optional[List[")
        # Repeated fields: None = unset (omitted on the wire, so requests
        # can distinguish "don't touch" from an explicit [] that clears);
        # responses deserialize missing to [] for iteration ergonomics.
        inits.append(f"    {field.name}: {ann} = {default}")
        froms.append(
            f"            {field.name}=(lambda v: {from_expr})"
            f"(obj.get({field.name!r}))"
            f" if obj.get({field.name!r}) is not None else "
            + ("[]" if repeated else "None") + ",")
        guard = f"self.{field.name} is not None"
        tos.append(
            f"        if {guard}:\n"
            f"            out[{field.name!r}] = "
            f"(lambda x: {to_expr})(self.{field.name})")
    lines.extend(inits)
    lines.append("")
    lines.append("    @classmethod")
    lines.append(f"    def from_json(cls, obj: dict) -> \"{name}\":")
    lines.append("        obj = obj or {}")
    lines.append("        return cls(")
    lines.extend(froms)
    lines.append("        )")
    lines.append("")
    lines.append("    def to_json(self) -> dict:")
    lines.append("        # None = unset (proto3 explicit presence): omitted")
    lines.append("        out: dict = {}")
    lines.extend(tos if tos else ["        pass"])
    lines.append("        return out")
    return "\n".join(lines)


def gen_rpc(method) -> str:
    opts = parse_method_options(method.options)
    http_method = opts.get("method", "POST")
    path = opts.get("path")
    if not path:
        raise ValueError(f"rpc {method.name} missing http_path option")
    req_type = "V1" + method.input_type.split(".")[-1]
    resp_type = "V1" + method.output_type.split(".")[-1]
    fname = snake(method.name)
    path_fields = [seg[1:-1] for seg in
                   [p for p in path.split("/") if p.startswith("{")]]
    body_lines = [
        f"def {fname}(session: Any, req: {req_type}) -> "
        + (f"Iterator[{resp_type}]" if opts.get("stream") else resp_type)
        + ":",
        f'    """{http_method} {path}"""',
        "    body = req.to_json()",
    ]
    fmt_path = path
    for pf in path_fields:
        fmt_path = fmt_path.replace(
            "{" + pf + "}",
            "{" + f"_path_param(body, {pf!r}, {method.name!r})" + "}")
    body_lines.append(f'    path = f"{fmt_path}"')
    if opts.get("stream"):
        # poll-stream: page with offset/limit until a short page arrives
        body_lines.extend([
            "    offset = int(body.pop('offset', 0) or 0)",
            "    limit = int(body.pop('limit', 0) or 0) or 1000",
            "    while True:",
            "        out = session.request(",
            "            'GET', path + f'?limit={limit}&offset={offset}')",
            f"        page = {resp_type}.from_json(out)",
            "        yield page",
            "        n = sum(len(v) for v in out.values()"
            " if isinstance(v, list))",
            "        if n < limit:",
            "            return",
            "        offset += n",
        ])
        return "\n".join(body_lines)
    if http_method == "GET":
        body_lines.extend([
            "    query = '&'.join(f'{k}={_q(v)}' for k, v in body.items()",
            "                     if not isinstance(v, (dict, list)) and"
            " v not in (None, ''))",
            "    if query:",
            "        path += '?' + query",
            f"    out = session.request('GET', path)",
        ])
    else:
        body_lines.append(
            f"    out = session.request({http_method!r}, path, body)")
    body_lines.append(f"    return {resp_type}.from_json(out)")
    return "\n".join(body_lines)


HEADER = '''"""GENERATED by bindings/generate_bindings_py.py — DO NOT EDIT.

Typed client over the DCT master's REST gateway, generated from
proto/dct/api/v1/api.proto (the schema source of truth; ≈ the reference's
generated harness/determined/common/api/bindings.py). Transport is any
object with ``request(method, path, body=None)`` — normally
determined_clone_tpu.api.client.MasterSession.
"""
# flake8: noqa
from __future__ import annotations

import dataclasses
import urllib.parse
from typing import Any, Iterator, List, Optional


def _q(segment: Any) -> str:
    return urllib.parse.quote(str(segment), safe="")


def _path_param(body: dict, name: str, rpc: str) -> str:
    """Pop a path parameter; an unset path param is a caller bug and must
    not silently route to a different endpoint."""
    val = body.pop(name, None)
    if val is None or val == "":
        raise ValueError(f"{rpc}: request field {name!r} is required "
                         "(it fills the URL path)")
    return _q(val)

'''


def generate() -> str:
    from google.protobuf import descriptor_pb2

    fds = descriptor_pb2.FileDescriptorSet.FromString(compile_descriptors())
    chunks = [HEADER]
    api_files = [f for f in fds.file if f.package == "dct.api.v1"
                 and f.name.endswith("api.proto")]
    for f in api_files:
        for msg in f.message_type:
            chunks.append(gen_message(msg))
            chunks.append("")
        for svc in f.service:
            chunks.append(f"# ---- service {svc.name} "
                          f"({len(svc.method)} RPCs) ----")
            chunks.append("")
            for method in svc.method:
                chunks.append(gen_rpc(method))
                chunks.append("")
    return "\n".join(chunks).rstrip() + "\n"


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--check", action="store_true",
                        help="fail if the checked-in bindings are stale")
    args = parser.parse_args()
    code = generate()
    compile(code, OUT_PATH, "exec")  # syntax-check before writing
    if args.check:
        with open(OUT_PATH) as f:
            if f.read() != code:
                print("bindings.py is stale — run "
                      "python bindings/generate_bindings_py.py",
                      file=sys.stderr)
                return 1
        print("bindings.py up to date")
        return 0
    with open(OUT_PATH, "w") as f:
        f.write(code)
    print(f"wrote {OUT_PATH} ({len(code.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
